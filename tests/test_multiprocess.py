"""True multi-process distributed training test.

Launches TWO separate python processes running the real ``training.py`` CLI,
rendezvousing through ``jax.distributed.initialize`` (coordinator = process 0,
the reference's MASTER_ADDR/MASTER_PORT contract) with one CPU device each —
so the fsdp=2 mesh spans PROCESS boundaries and every collective crosses a
real process gap, unlike the 8-virtual-device single-process tests.

This is the test the reference could never write (its multi-node behavior was
only validated on a live cluster — SURVEY.md §4): rendezvous, cross-process
batch assembly, sharded compute, host-0-only artifact writes, and the shared
summary contract, all on one machine.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_training(tmp_path):
    from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet

    jsonl = tmp_path / "qa.jsonl"
    with open(jsonl, "w") as f:
        for i in range(48):
            f.write(json.dumps({
                "topic": "Knots",
                "question": f"question {i}?",
                "answer": f"answer {i}: " + "word " * (3 + i % 4),
            }) + "\n")
    convert_jsonl_to_parquet(str(jsonl), str(tmp_path / "qa_dataset.parquet"), verbose=False)

    out = tmp_path / "outputs"
    cfg = {
        "model_name": "tiny-random",
        "model_preset": "tiny",
        "tokenizer_path": "byte-chatml",
        "system_prompt": "You are an expert.",
        "data_dir": str(tmp_path),
        "dataset_file": "qa_dataset.parquet",
        "output_dir": str(out),
        "epochs": 1,
        "per_device_batch_size": 2,
        "gradient_accumulation_steps": 2,
        "learning_rate": 2e-3,
        "max_seq_length": 128,
        "eval_steps": 4,
        "logging_steps": 2,
        "save_steps": 100,
        "mesh": {"data": 1, "fsdp": 2, "tensor": 1, "seq": 1},
        "use_native_loader": False,
        "heartbeat": False,
        # exercise the cross-host checksum exchange (runtime/desync.py) in a
        # REAL multi-process world every few steps
        "desync_check_steps": 4,
    }
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))

    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            WORLD_SIZE="2",
            RANK=str(rank),
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
            JAX_PLATFORMS="cpu",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, os.path.join(REPO, "training.py"),
                 "--config", str(cfg_path), "--platform", "cpu"],
                env=env,
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )

    outputs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process training timed out (rendezvous hang?)")
        outputs.append(stdout)

    for rank, (p, text) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{text[-4000:]}"

    # host-0 artifact contract; host 1 must NOT have written duplicates
    assert (out / "best_model" / "model.safetensors").exists()
    with open(out / "training_summary.json") as f:
        summary = json.load(f)
    assert summary["world_size"] == 2
    assert summary["distributed_training"] is True
    history = json.loads((out / "training_history.json").read_text())
    losses = [h["loss"] for h in history if "loss" in h]
    assert losses and all(np.isfinite(l) for l in losses)
    # the completion banner is host-0-gated (reference rank-0 prints)
    assert "completed successfully" in outputs[0]
    assert "completed successfully" not in outputs[1]


@pytest.mark.slow
def test_two_process_cross_host_sequence_parallel(tmp_path):
    """The seq axis SPANS process boundaries (VERDICT r1 #4): 2 processes x 1
    device, mesh seq=2, ring attention — each host loads the same batch rows
    and its device holds a sequence slice; the ring's ppermute crosses the
    process gap every step."""
    from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet

    jsonl = tmp_path / "qa.jsonl"
    with open(jsonl, "w") as f:
        for i in range(32):
            f.write(json.dumps({
                "topic": "Knots",
                "question": f"question {i}?",
                "answer": f"answer {i}: " + "word " * (3 + i % 4),
            }) + "\n")
    convert_jsonl_to_parquet(str(jsonl), str(tmp_path / "qa_dataset.parquet"), verbose=False)

    out = tmp_path / "outputs"
    cfg = {
        "model_name": "tiny-random",
        "model_preset": "tiny",
        "tokenizer_path": "byte-chatml",
        "system_prompt": "You are an expert.",
        "data_dir": str(tmp_path),
        "dataset_file": "qa_dataset.parquet",
        "output_dir": str(out),
        "epochs": 1,
        "per_device_batch_size": 2,
        "gradient_accumulation_steps": 2,
        "learning_rate": 2e-3,
        "max_seq_length": 128,
        "eval_steps": 4,
        "logging_steps": 2,
        "save_steps": 100,
        "attention_impl": "ring",
        "mesh": {"data": 1, "fsdp": 1, "tensor": 1, "seq": 2},
        "use_native_loader": False,
        "heartbeat": False,
    }
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))

    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            WORLD_SIZE="2",
            RANK=str(rank),
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
            JAX_PLATFORMS="cpu",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, os.path.join(REPO, "training.py"),
                 "--config", str(cfg_path), "--platform", "cpu"],
                env=env,
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )

    outputs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("cross-host seq-parallel training timed out")
        outputs.append(stdout)

    for rank, (p, text) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{text[-4000:]}"

    assert (out / "best_model" / "model.safetensors").exists()
    history = json.loads((out / "training_history.json").read_text())
    losses = [h["loss"] for h in history if "loss" in h]
    assert len(losses) >= 2 and all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], f"no learning: {losses[0]} -> {losses[-1]}"
    assert "completed successfully" in outputs[0]


_DECODE_PROBE = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["MASTER_ADDR"] + ":" + os.environ["MASTER_PORT"],
    num_processes=int(os.environ["WORLD_SIZE"]),
    process_id=int(os.environ["RANK"]),
)
import jax.numpy as jnp
from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.generate import make_tp_mesh
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params

mc = get_preset("tiny")
params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
mesh = make_tp_mesh(2)  # spans BOTH single-device processes
assert len({d.process_index for d in mesh.devices.flat}) == 2
gen = Generator(params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32,
                eos_token_ids=[], mesh=mesh)
tok = ByteChatMLTokenizer()
cfg = GenerationConfig(max_new_tokens=8, do_sample=False, repetition_penalty=1.1)
out = gen.generate_batch(
    [tok.encode("the quick brown fox"), tok.encode("water water water")], cfg
)
if jax.process_index() == 0:
    with open(sys.argv[1], "w") as f:
        json.dump(out, f)
print("DECODE PROBE OK", jax.process_index())
"""


@pytest.mark.slow
def test_two_process_tensor_parallel_decode_parity(tmp_path):
    """Multi-host inference (VERDICT r2 #5): a tensor=2 mesh spanning TWO
    single-device processes decodes with greedy BIT-PARITY (f32) against the
    single-process meshless Generator — weights placed via global arrays,
    TP psums crossing a real process boundary every layer."""
    port = _free_port()
    out_file = tmp_path / "decode.json"
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            WORLD_SIZE="2",
            RANK=str(rank),
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
            JAX_PLATFORMS="cpu",
            PYTHONPATH=REPO,
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _DECODE_PROBE, str(out_file)],
                env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    outputs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("2-process TP decode timed out (rendezvous hang?)")
        outputs.append(stdout)
    for rank, (p, text) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{text[-4000:]}"

    # single-process reference: same seeded init, no mesh
    import jax
    import jax.numpy as jnp

    from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
    from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import init_params

    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    tok = ByteChatMLTokenizer()
    ref = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    cfg = GenerationConfig(max_new_tokens=8, do_sample=False, repetition_penalty=1.1)
    expected = ref.generate_batch(
        [tok.encode("the quick brown fox"), tok.encode("water water water")], cfg
    )
    got = json.loads(out_file.read_text())
    assert got == expected, f"multi-host TP decode diverged: {got} != {expected}"


_COORD_PROBE = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["MASTER_ADDR"] + ":" + os.environ["MASTER_PORT"],
    num_processes=int(os.environ["WORLD_SIZE"]),
    process_id=int(os.environ["RANK"]),
)
import jax.numpy as jnp
from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.generate import make_tp_mesh
from llm_fine_tune_distributed_tpu.infer.multihost import MultihostCoordinator, follow
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params

mc = get_preset("tiny")
params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
tok = ByteChatMLTokenizer()
gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[],
                mesh=make_tp_mesh(2))
if jax.process_index() == 0:
    coord = MultihostCoordinator(gen)
    outs = []
    # two batches with DIFFERENT configs: followers must mirror both
    outs.append(coord.generate_batch(
        [tok.encode("the quick brown fox")],
        GenerationConfig(max_new_tokens=6, do_sample=False, repetition_penalty=1.1)))
    outs.append(coord.generate_batch(
        [tok.encode("water water"), tok.encode("abc abc")],
        GenerationConfig(max_new_tokens=4, do_sample=True, temperature=0.8), seed=7))
    coord.stop()
    with open(sys.argv[1], "w") as f:
        json.dump(outs, f)
else:
    follow(gen)
print("COORD PROBE OK", jax.process_index())
"""


@pytest.mark.slow
def test_two_process_serving_coordinator(tmp_path):
    """The multi-host serving bridge: host 0 broadcasts (prompts, config,
    seed) per batch, the follower mirrors the exact generate_batch calls
    (greedy AND sampled, different shapes), and stop() releases it."""
    port = _free_port()
    out_file = tmp_path / "coord.json"
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            WORLD_SIZE="2", RANK=str(rank),
            MASTER_ADDR="127.0.0.1", MASTER_PORT=str(port),
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
            JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _COORD_PROBE, str(out_file)],
                env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    outputs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("serving-coordinator probe timed out")
        outputs.append(stdout)
    for rank, (p, text) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{text[-4000:]}"
        assert f"COORD PROBE OK {rank}" in text
    outs = json.loads(out_file.read_text())
    assert len(outs) == 2 and len(outs[1]) == 2


_ELASTIC_PROBE = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["MASTER_ADDR"] + ":" + os.environ["MASTER_PORT"],
    num_processes=int(os.environ["WORLD_SIZE"]),
    process_id=int(os.environ["RANK"]),
)
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
from llm_fine_tune_distributed_tpu.parallel.optimizer import build_optimizer
from llm_fine_tune_distributed_tpu.parallel.sharding import _validate_spec, param_spec
from llm_fine_tune_distributed_tpu.runtime.mesh import data_parallel_size, make_mesh
from llm_fine_tune_distributed_tpu.train.checkpoints import CheckpointManager
from llm_fine_tune_distributed_tpu.train.state import TrainState
from llm_fine_tune_distributed_tpu.train.step import build_train_step, jit_train_step
from llm_fine_tune_distributed_tpu.utils.tree import split_by_mask

mode, ckpt_dir, dump = sys.argv[1], sys.argv[2], sys.argv[3]
world = jax.process_count()
mesh = make_mesh(MeshConfig(data=1, fsdp=world, tensor=1, seq=1))
mc = get_preset("tiny")
tc = TrainConfig(model_preset="tiny", per_device_batch_size=1,
                 gradient_accumulation_steps=2, max_seq_length=64)

params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
trainable, frozen = split_by_mask(params, trainable_mask(params, mc, tc))
frozen = {k: v.astype(jnp.bfloat16) for k, v in frozen.items()}
put = lambda flat: {
    k: jax.device_put(
        v, NamedSharding(mesh, _validate_spec(param_spec(k, v.ndim), v.shape, mesh))
    )
    for k, v in flat.items()
}
trainable, frozen = put(trainable), put(frozen)
opt = build_optimizer(tc, None, total_steps=8, data_parallel_size=data_parallel_size(mesh))
rep = NamedSharding(mesh, P())
full_devices = set(np.asarray(mesh.devices).flat)
from jax.experimental import multihost_utils


def on_full_mesh(x):
    # same normalization the trainer applies: scalar opt leaves can come out
    # single-device; route them host-side (eager cross-host device_put is
    # unsupported on the CPU backend) and re-place replicated
    if getattr(x, "sharding", None) and set(x.sharding.device_set) == full_devices:
        return x
    local = np.zeros(x.shape, x.dtype)
    if getattr(x, "is_fully_addressable", True):
        local = np.asarray(jax.device_get(x))
    val = multihost_utils.broadcast_one_to_all(local)
    return jax.device_put(val, rep)


state = TrainState(
    step=jax.device_put(jnp.zeros((), jnp.int32), rep),
    trainable=trainable,
    frozen=frozen,
    opt_state=jax.tree.map(on_full_mesh, jax.jit(opt.init)(trainable)),
)
mgr = CheckpointManager(ckpt_dir)
if mode == "save":
    act = NamedSharding(mesh, P(("data", "fsdp"), None, None))
    step_fn = jit_train_step(build_train_step(mc, tc, opt, activation_sharding=act))
    rng = np.random.RandomState(0)
    bsz = data_parallel_size(mesh)
    sh = NamedSharding(mesh, P(None, ("data", "fsdp")))
    for i in range(2):
        batch = {
            "input_ids": jax.device_put(
                rng.randint(0, mc.vocab_size, (2, bsz, 64)).astype(np.int32), sh),
            "loss_mask": jax.device_put(np.ones((2, bsz, 64), np.float32), sh),
            "attention_mask": jax.device_put(np.ones((2, bsz, 64), np.int32), sh),
        }
        state, _ = step_fn(state, batch)
    mgr.save(int(jax.device_get(state.step)), state)
    mgr.wait()
else:
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding), state
    )
    state = mgr.restore(mgr.latest_step, abstract)
mgr.close()

# dump every leaf (trainable + frozen + opt moments + step) from host 0,
# resharded replicated so the bytes are host-fetchable on any world size
leaves, _ = jax.tree_util.tree_flatten_with_path(
    {"step": state.step, "trainable": state.trainable,
     "frozen": state.frozen, "opt": state.opt_state}
)
out = {}
for path, leaf in leaves:
    key = jax.tree_util.keystr(path)
    # eager cross-host device_put is unsupported on the CPU backend; a
    # compiled identity reshard (all-gather collective) is
    v = jax.jit(lambda x: x, out_shardings=rep)(leaf)
    if jax.process_index() == 0:
        out[key] = np.asarray(v)
if jax.process_index() == 0:
    np.savez(dump, **out)
print("ELASTIC PROBE OK", mode, world, jax.process_index())
"""


def _run_elastic_phase(mode, world, ckpt_dir, dump):
    port = _free_port()
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        env.update(
            WORLD_SIZE=str(world), RANK=str(rank),
            MASTER_ADDR="127.0.0.1", MASTER_PORT=str(port),
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
            JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _ELASTIC_PROBE, mode, str(ckpt_dir), str(dump)],
                env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    outputs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"elastic {mode} (world={world}) timed out")
        outputs.append(stdout)
    for rank, (p, text) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"{mode} world={world} rank {rank} failed:\n{text[-4000:]}"


def _assert_dumps_identical(a_path, b_path):
    a, b = np.load(a_path), np.load(b_path)
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.mark.slow
def test_elastic_resume_four_to_two_processes(tmp_path):
    """The JobSet restart reality (VERDICT r4 #6): a sharded Orbax save from
    FOUR processes restores into TWO — every leaf (params, frozen, Adam
    moments, step) bit-identical. Orbax stores global arrays; the fsdp axis
    resize is pure resharding."""
    _run_elastic_phase("save", 4, tmp_path / "ckpt", tmp_path / "saved.npz")
    _run_elastic_phase("restore", 2, tmp_path / "ckpt", tmp_path / "restored.npz")
    _assert_dumps_identical(tmp_path / "saved.npz", tmp_path / "restored.npz")


@pytest.mark.slow
def test_elastic_resume_two_to_four_processes(tmp_path):
    """The inverse resize: save from TWO processes, restore into FOUR."""
    _run_elastic_phase("save", 2, tmp_path / "ckpt", tmp_path / "saved.npz")
    _run_elastic_phase("restore", 4, tmp_path / "ckpt", tmp_path / "restored.npz")
    _assert_dumps_identical(tmp_path / "saved.npz", tmp_path / "restored.npz")
