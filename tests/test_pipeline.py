"""Pipeline parallelism (GPipe over a ``pipe`` mesh axis): exact parity with
the plain forward, through forward AND backward (jax.grad through the
scan+ppermute schedule)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params
from llm_fine_tune_distributed_tpu.parallel.pipeline import (
    pipeline_forward,
    pipeline_loss_fn,
    stack_stage_params,
    stage_sharding,
)

B, SEQ = 8, 64


@pytest.fixture(scope="module")
def setup(eight_devices):
    config = get_preset("tiny").replace(no_rope_layers=(), num_layers=4)
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, config.vocab_size, (B, SEQ)), jnp.int32
    )
    return config, params, ids


def _mesh(n_stages):
    return Mesh(np.array(jax.devices()[:n_stages]), ("pipe",))


@pytest.mark.parametrize("n_stages,n_micro", [(2, 2), (4, 2), (2, 4), (4, 8)])
def test_pipeline_forward_matches_plain(setup, n_stages, n_micro):
    config, params, ids = setup
    mesh = _mesh(n_stages)
    stacked = jax.device_put(
        stack_stage_params(params, config, n_stages), stage_sharding(mesh)
    )
    logits_pipe = pipeline_forward(
        params, stacked, ids, config, mesh, n_micro,
        compute_dtype=jnp.float32, remat_blocks=False,
    )
    logits_plain, _ = forward(
        params, ids, config, compute_dtype=jnp.float32, logits_dtype=jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(logits_pipe), np.asarray(logits_plain), atol=2e-4, rtol=2e-4
    )


@pytest.mark.slow
def test_pipeline_grads_match_plain(setup):
    """Gradients through the pipelined schedule == plain-model gradients,
    for both the replicated params and the stacked (stage-sharded) layers."""
    import optax

    config, params, ids = setup
    mesh = _mesh(4)
    stacked = jax.device_put(
        stack_stage_params(params, config, 4), stage_sharding(mesh)
    )
    mask = jnp.ones((B, SEQ), jnp.float32)
    batch = {"input_ids": ids, "loss_mask": mask}

    def loss_pipe(params, stacked):
        return pipeline_loss_fn(
            params, stacked, batch, config, mesh, 4, compute_dtype=jnp.float32
        )

    def loss_plain(params):
        logits, _ = forward(
            params, ids, config, compute_dtype=jnp.float32, logits_dtype=jnp.float32
        )
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], ids[:, 1:]
        )
        return (ce * mask[:, 1:]).sum() / mask[:, 1:].sum()

    (lp, (g_params, g_stacked)) = jax.value_and_grad(loss_pipe, argnums=(0, 1))(
        params, stacked
    )
    lr, g_plain = jax.value_and_grad(loss_plain)(params)
    assert float(lp) == pytest.approx(float(lr), rel=1e-5)

    # embedding grads (replicated side)
    np.testing.assert_allclose(
        np.asarray(g_params["model"]["embed_tokens"]["weight"]),
        np.asarray(g_plain["model"]["embed_tokens"]["weight"]),
        atol=2e-5, rtol=2e-4,
    )
    # per-layer grads: stacked [L, ...] rows must equal the plain per-layer grads
    for i in range(4):
        np.testing.assert_allclose(
            np.asarray(g_stacked["self_attn"]["q_proj"]["kernel"][i]),
            np.asarray(g_plain["model"]["layers"][str(i)]["self_attn"]["q_proj"]["kernel"]),
            atol=2e-5, rtol=2e-4, err_msg=f"layer {i} q_proj grad",
        )
        np.testing.assert_allclose(
            np.asarray(g_stacked["mlp"]["down_proj"]["kernel"][i]),
            np.asarray(g_plain["model"]["layers"][str(i)]["mlp"]["down_proj"]["kernel"]),
            atol=2e-5, rtol=2e-4, err_msg=f"layer {i} down_proj grad",
        )


def test_pipeline_nope_interleaved_matches_plain(setup):
    """SmolLM3-style NoPE interleaving: per-layer RoPE flags ride the layer
    scan as data, so the pipelined model matches the plain one exactly."""
    config, params, ids = setup
    nope = config.replace(no_rope_layers=(1, 0, 1, 0))
    mesh = _mesh(2)
    stacked = jax.device_put(
        stack_stage_params(params, nope, 2), stage_sharding(mesh)
    )
    logits_pipe = pipeline_forward(
        params, stacked, ids, nope, mesh, 2,
        compute_dtype=jnp.float32, remat_blocks=False,
    )
    logits_plain, _ = forward(
        params, ids, nope, compute_dtype=jnp.float32, logits_dtype=jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(logits_pipe), np.asarray(logits_plain), atol=2e-4, rtol=2e-4
    )


def test_stack_stage_params_layout(setup):
    config, params, _ = setup
    stacked = stack_stage_params(params, config, 2)
    assert stacked["self_attn"]["q_proj"]["kernel"].shape[0] == config.num_layers
    np.testing.assert_array_equal(
        np.asarray(stacked["mlp"]["up_proj"]["kernel"][2]),
        np.asarray(params["model"]["layers"]["2"]["mlp"]["up_proj"]["kernel"]),
    )
    with pytest.raises(ValueError, match="divisible"):
        stack_stage_params(params, config, 3)


def test_pipeline_padded_batch_matches_plain(setup):
    """Right-padded batches: the padding mask must ride the schedule so real
    queries never attend pad keys (same semantics as the plain forward)."""
    config, params, ids = setup
    mesh = _mesh(2)
    stacked = jax.device_put(
        stack_stage_params(params, config, 2), stage_sharding(mesh)
    )
    lengths = np.array([64, 50, 33, 64, 12, 64, 40, 64])
    pm = jnp.asarray((np.arange(SEQ)[None, :] < lengths[:, None]).astype(np.float32))
    logits_pipe = pipeline_forward(
        params, stacked, ids, config, mesh, 2,
        padding_mask=pm, compute_dtype=jnp.float32, remat_blocks=False,
    )
    logits_plain, _ = forward(
        params, ids, config, padding_mask=pm,
        compute_dtype=jnp.float32, logits_dtype=jnp.float32,
    )
    real = np.asarray(pm) > 0
    np.testing.assert_allclose(
        np.asarray(logits_pipe)[real], np.asarray(logits_plain)[real],
        atol=2e-4, rtol=2e-4,
    )


@pytest.mark.slow
def test_pipeline_chunked_loss_matches_full(setup):
    """loss_chunk_size path (large-vocab HBM saver) == full-unembed path."""
    config, params, ids = setup
    mesh = _mesh(2)
    stacked = jax.device_put(
        stack_stage_params(params, config, 2), stage_sharding(mesh)
    )
    batch = {"input_ids": ids, "loss_mask": jnp.ones((B, SEQ), jnp.float32)}
    full = pipeline_loss_fn(params, stacked, batch, config, mesh, 2,
                            compute_dtype=jnp.float32)
    chunked = pipeline_loss_fn(params, stacked, batch, config, mesh, 2,
                               compute_dtype=jnp.float32, loss_chunk_size=16)
    assert float(full) == pytest.approx(float(chunked), rel=1e-5)
