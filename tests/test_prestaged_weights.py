"""Pre-staged real-weights path (VERDICT r4 #5).

No-egress environments cannot fetch HF Hub weights, so the reference's end
oracle (real SmolLM3 answering the golden questions better after tuning)
runs here via PRE-STAGED weights: ``MODEL_NAME=/path/to/dir`` with real-format
HF files. Nothing previously proved that path end-to-end. This test stages a
tiny HF-layout checkpoint — safetensors weights, HF config.json, and a REAL
``tokenizers``-library BPE tokenizer (tokenizer.json + tokenizer_config.json
with a ChatML chat template, the exact file format a hub snapshot ships) —
then trains from it through the normal trainer (architecture resolved from
the dir's config.json via MODEL_PRESET=none) and runs the eval_golden CLI
against the produced best_model/, so the day egress exists the oracle runs
unchanged.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig
from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet

CHATML_TEMPLATE = (
    "{% for message in messages %}"
    "{{ '<|im_start|>' + message['role'] + '\n' + message['content'] + '<|im_end|>' + '\n' }}"
    "{% endfor %}"
    "{% if add_generation_prompt %}{{ '<|im_start|>assistant\n' }}{% endif %}"
)


def _build_real_hf_tokenizer(save_dir: str, corpus):
    """A genuine HF fast tokenizer built offline: ByteLevel BPE trained on
    the test corpus, ChatML specials, saved in the standard snapshot layout."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers
    from transformers import PreTrainedTokenizerFast

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=384,
        special_tokens=["<|im_start|>", "<|im_end|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tok.train_from_iterator(corpus, trainer)
    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok,
        eos_token="<|im_end|>",
        pad_token="<|im_end|>",
        chat_template=CHATML_TEMPLATE,
    )
    fast.save_pretrained(save_dir)
    return fast


@pytest.mark.slow
def test_prestaged_hf_dir_trains_and_answers_golden_questions(tmp_path):
    from llm_fine_tune_distributed_tpu.config import ModelConfig
    from llm_fine_tune_distributed_tpu.models.configs import to_hf_dict
    from llm_fine_tune_distributed_tpu.models.hf_io import save_hf_checkpoint
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    # --- stage the "downloaded" checkpoint dir ---------------------------
    staged = tmp_path / "staged_model"
    staged.mkdir()
    rows = [
        {"topic": "Knots", "question": f"question {i}?",
         "answer": f"answer {i}: tie the loop and pull."}
        for i in range(48)
    ]
    corpus = [r["question"] + " " + r["answer"] for r in rows]
    tok = _build_real_hf_tokenizer(str(staged), corpus)
    assert (staged / "tokenizer.json").exists()  # the real HF file format
    assert (staged / "tokenizer_config.json").exists()

    mc = ModelConfig(
        name="llama",  # a real HF model_type: exercises the generic path
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        rope_theta=10_000.0,
        max_position_embeddings=512,
        tie_word_embeddings=True,
    )
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    save_hf_checkpoint(params, str(staged))
    with open(staged / "config.json", "w") as f:
        json.dump(to_hf_dict(mc), f)

    # --- dataset ----------------------------------------------------------
    jsonl = tmp_path / "qa.jsonl"
    with open(jsonl, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    convert_jsonl_to_parquet(str(jsonl), str(tmp_path / "qa_dataset.parquet"), verbose=False)

    # --- train FROM the staged dir (architecture from its config.json) ---
    out = tmp_path / "out"
    cfg = TrainConfig(
        model_name=str(staged),
        model_preset=None,          # MODEL_PRESET=none contract
        tokenizer_path=None,        # -> model_name dir (real HF files)
        system_prompt="Be brief.",
        data_dir=str(tmp_path),
        dataset_file="qa_dataset.parquet",
        output_dir=str(out),
        epochs=1,
        per_device_batch_size=2,
        gradient_accumulation_steps=2,
        learning_rate=1e-3,
        max_seq_length=96,
        eval_steps=5,
        save_steps=0,
        unfreeze_last_n_layers=1,
        use_native_loader=False,
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=1),
    )
    trainer = SFTTrainer(cfg)
    # the staged REAL tokenizer is in play, not the byte fallback
    assert trainer.tokenizer.__class__.__name__ == "PreTrainedTokenizerFast"
    assert trainer.model_config.name == "llama"
    assert trainer.model_config.hidden_size == 64
    summary = trainer.train()
    assert np.isfinite(summary["final_train_loss"])

    best = out / "best_model"
    assert (best / "config.json").exists()
    assert (best / "tokenizer.json").exists()  # real tokenizer re-exported

    # --- the reference oracle runs unchanged against the artifact --------
    report = tmp_path / "golden.json"
    r = subprocess.run(
        [
            sys.executable, "eval_golden.py",
            "--tuned-dir", str(best),
            "--report", str(report),
            "--max-new-tokens", "8",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert report.exists() or "How many cups" in r.stdout
