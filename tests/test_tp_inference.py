"""Tensor-parallel inference (VERDICT r1 #5): a Generator given a tp mesh
shards weights (and the KV cache by propagation) and produces the same
greedy tokens as single-device decode; sampled decode stays seeded-
deterministic; the weights are actually distributed (per-device shards)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.generate import make_tp_mesh
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params


@pytest.fixture(scope="module")
def setup():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return mc, params, ByteChatMLTokenizer()


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_greedy_matches_single_device(setup, tp):
    mc, params, tok = setup
    solo = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    sharded = Generator(
        params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[],
        mesh=make_tp_mesh(tp),
    )
    cfg = GenerationConfig(max_new_tokens=12, do_sample=False, repetition_penalty=1.1)
    for text in ("hello world", "ab ab ab"):
        prompt = tok.encode(text)
        assert sharded.generate_ids(prompt, cfg) == solo.generate_ids(prompt, cfg)


def test_tp_weights_are_sharded(setup):
    mc, params, tok = setup
    g = Generator(
        params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[],
        mesh=make_tp_mesh(4),
    )
    # a column-parallel kernel: out dim sharded 4 ways
    k = g.params["model"]["layers"]["0"]["self_attn"]["q_proj"]["kernel"]
    shard = k.addressable_shards[0].data
    assert shard.shape[1] * 4 == k.shape[1], (
        f"q_proj not tensor-sharded: shard {shard.shape} of {k.shape}"
    )


def test_tp_sampled_deterministic_and_valid(setup):
    mc, params, tok = setup
    g = Generator(
        params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[],
        mesh=make_tp_mesh(2),
    )
    cfg = GenerationConfig(max_new_tokens=8, do_sample=True)
    prompt = tok.encode("hello")
    a = g.generate_ids(prompt, cfg, seed=3)
    assert a == g.generate_ids(prompt, cfg, seed=3)
    assert all(0 <= t < mc.vocab_size for t in a)


def test_tp_speculative_greedy_matches(setup):
    """The speculative decoder also runs sharded (its gather/scatter fori
    loop partitions; drafts verify identically)."""
    mc, params, tok = setup
    solo = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    sharded = Generator(
        params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[],
        mesh=make_tp_mesh(2),
    )
    cfg = GenerationConfig(
        max_new_tokens=10, do_sample=False, repetition_penalty=1.0,
        speculative_lookup=3,
    )
    prompt = tok.encode("ab ab ab ab")
    assert sharded.generate_ids(prompt, cfg) == solo.generate_ids(prompt, cfg)


def test_tp_batched_ragged(setup):
    mc, params, tok = setup
    sharded = Generator(
        params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[],
        mesh=make_tp_mesh(2),
    )
    solo = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    cfg = GenerationConfig(max_new_tokens=6, do_sample=False, repetition_penalty=1.0)
    prompts = [tok.encode(t) for t in ("one", "two tokens here")]
    assert sharded.generate_batch(prompts, cfg) == solo.generate_batch(prompts, cfg)


@pytest.mark.slow
def test_moe_tp_ep_decode_matches_single_device():
    """Mixtral-style serving: a tensor x expert inference mesh decodes
    identically to single-device (expert weights shard over `expert`,
    dropless dispatch under the KV cache)."""
    from llm_fine_tune_distributed_tpu.config import MeshConfig
    from llm_fine_tune_distributed_tpu.runtime.mesh import make_mesh

    mc = get_preset("tiny_moe")
    params = init_params(jax.random.PRNGKey(1), mc, dtype=jnp.float32)
    tok = ByteChatMLTokenizer()
    solo = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, tensor=2, seq=1, expert=4, pipe=1))
    ep = Generator(
        params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[], mesh=mesh
    )
    cfg = GenerationConfig(max_new_tokens=8, do_sample=False, repetition_penalty=1.0)
    prompt = tok.encode("hello world")
    assert ep.generate_ids(prompt, cfg) == solo.generate_ids(prompt, cfg)


@pytest.mark.slow
def test_llama3_70b_tp_decode_program_lowers():
    """The 70B preset's TP decode program compiles (abstractly) over a
    tensor=8 mesh: every weight in the decode path is partitionable, which
    is the property that makes the preset servable on a real slice. Uses
    jax.eval_shape-style lowering — no 70B params are materialized."""
    from llm_fine_tune_distributed_tpu.config import MeshConfig
    from llm_fine_tune_distributed_tpu.models.transformer import (
        forward,
        init_cache,
        unembed,
    )
    from llm_fine_tune_distributed_tpu.parallel.sharding import (
        param_sharding_rules,
    )
    from llm_fine_tune_distributed_tpu.runtime.mesh import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    mc = get_preset("llama3_70b")
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, tensor=8, seq=1, expert=1, pipe=1))
    act = NamedSharding(mesh, P())

    def shapes_fn(rng):
        return init_params(rng, mc, dtype=jnp.bfloat16)

    params_shapes = jax.eval_shape(shapes_fn, jax.random.PRNGKey(0))
    shardings = param_sharding_rules(params_shapes, mesh)

    def step(params, tok, cache):
        hidden, cache = forward(
            params, tok, mc, cache=cache, cache_pos=8,
            compute_dtype=jnp.bfloat16, output_hidden=True,
            activation_sharding=act,
        )
        return unembed(params, hidden[:, -1], mc, compute_dtype=jnp.bfloat16, mesh=mesh), cache

    cache_shapes = jax.eval_shape(lambda: init_cache(mc, 1, 64, dtype=jnp.bfloat16))
    tok = jax.ShapeDtypeStruct((1, 1), jnp.int32)
    lowered = (
        jax.jit(step)
        .lower(
            jax.tree.map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
                params_shapes, shardings,
            ),
            tok,
            cache_shapes,
        )
    )
    hlo = lowered.as_text()
    assert "sharding" in hlo  # the program is genuinely partitioned
