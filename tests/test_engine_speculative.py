"""Speculative decoding inside the continuous-batching engines
(infer/engine.py + the fused draft/verify steps in infer/generate.py).

Pins the tentpole contracts: greedy speculative output is BIT-IDENTICAL to
solo ``generate_ids`` on both engines with live (sampled, non-speculative)
neighbors in the batch; sampled speculative output is deterministic in
(request, seed) regardless of co-residents; per-slot variable acceptance
advances lengths correctly across paged block boundaries; EOS inside an
accepted draft run stops exactly at EOS; per-request telemetry attributes
each request's OWN draft counts."""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
    _prompt_lookup,
)
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params

# repetitive prompts make the tiny random-init model loop under greedy
# decode, so prompt-lookup finds its trailing bigram and drafting engages
# (same trick as the solo speculative tests in tests/test_generate.py)
SPEC = GenerationConfig(max_new_tokens=12, do_sample=False, speculative_lookup=4)
GREEDY = GenerationConfig(max_new_tokens=12, do_sample=False)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32, eos_token_ids=[]
    )


def _spec_engine(generator, kind, **kw):
    if kind == "paged":
        kw.setdefault("block_len", 8)
        kw.setdefault("prefill_chunk", 32)
        return PagedContinuousBatchingEngine(
            generator, slots=4, buf_len=96, prompt_bucket=16,
            speculative_k=4, **kw,
        )
    return ContinuousBatchingEngine(
        generator, slots=4, buf_len=96, prompt_bucket=16, speculative_k=4, **kw
    )


def _prompts():
    tok = ByteChatMLTokenizer()
    return [
        tok.encode(t)
        for t in (
            "water water water water water",
            "abc abc abc abc abc",
            "the quick brown fox",
        )
    ]


def test_prompt_lookup_host_helper():
    import numpy as np

    ctx = np.asarray([5, 6, 7, 8, 5, 6], np.int32)
    # trailing bigram (5,6) recurs at 0 -> draft continues with 7, 8, 5
    assert _prompt_lookup(ctx, 3).tolist() == [7, 8, 5]
    # truncated near the end of the match window
    assert _prompt_lookup(ctx, 8).tolist() == [7, 8, 5, 6]
    # no recurrence / too short -> empty
    assert _prompt_lookup(np.asarray([1, 2, 3], np.int32), 4).size == 0
    assert _prompt_lookup(np.asarray([1, 2], np.int32), 4).size == 0


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_greedy_spec_bit_identical_with_mixed_neighbors(generator, kind):
    """The headline guarantee: greedy speculative requests decoded while
    their neighbors are a live SAMPLED request and a live NON-speculative
    greedy request reproduce solo generate_ids bit-for-bit — mixed
    spec/non-spec/sampled traffic shares one fused verify program."""
    prompts = _prompts()
    solo_spec = [generator.generate_ids(p, SPEC) for p in prompts]
    solo_plain = generator.generate_ids(prompts[2], GREEDY)
    # solo speculation is already pinned exact vs greedy (test_generate.py);
    # re-assert here so an upstream regression fails THIS file loudly too
    assert solo_spec[2] == solo_plain

    engine = _spec_engine(generator, kind)
    sampled_cfg = GenerationConfig(
        max_new_tokens=48, do_sample=True, temperature=1.0
    )
    results = [None] * len(prompts)
    plain_result = [None]

    def occupy():
        engine.submit(prompts[0], sampled_cfg, seed=11, timeout=240)

    def ask(i):
        results[i] = engine.submit(prompts[i], SPEC, timeout=240)

    def ask_plain():
        plain_result[0] = engine.submit(prompts[2], GREEDY, timeout=240)

    occupier = threading.Thread(target=occupy)
    occupier.start()
    time.sleep(0.05)  # the sampled occupant takes its slot first
    threads = [threading.Thread(target=ask, args=(i,)) for i in range(len(prompts))]
    threads.append(threading.Thread(target=ask_plain))
    for t in threads:
        t.start()
    for t in threads + [occupier]:
        t.join(timeout=240)
    assert results == solo_spec
    assert plain_result[0] == solo_plain


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_sampled_spec_deterministic_in_request_seed(generator, kind):
    """Sampled speculative output depends only on (request, seed): every
    live slot consumes a FIXED number of RNG subkeys per tick whether or
    not its drafts are accepted, so co-residents and acceptance patterns
    cannot perturb a request's stream."""
    prompts = _prompts()
    engine = _spec_engine(generator, kind)
    cfg = GenerationConfig(
        max_new_tokens=10, do_sample=True, temperature=1.0, speculative_lookup=4
    )
    a = engine.submit(prompts[0], cfg, seed=7, timeout=240)
    # replay with neighbors present: same seed must reproduce exactly
    results = {}

    def ask(tag, seed):
        results[tag] = engine.submit(prompts[0], cfg, seed=seed, timeout=240)

    threads = [
        threading.Thread(target=ask, args=("same", 7)),
        threading.Thread(target=ask, args=("other", 8)),
        threading.Thread(
            target=lambda: engine.submit(prompts[1], cfg, seed=9, timeout=240)
        ),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    assert results["same"] == a
    assert results["other"] != a  # different seed -> different stream


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_eos_inside_accepted_run_stops_exactly(generator, kind):
    """An EOS token verified mid-run (inside a tick's accepted drafts) must
    end the request AT the EOS — no token after it leaks out, on either
    engine (the tick-local `done` mask gates later positions)."""
    prompts = _prompts()
    open_out = generator.generate_ids(prompts[0], SPEC)
    assert len(open_out) >= 4
    eos = open_out[2]  # a token the model emits mid-stream
    gen2 = Generator(
        generator.params, generator.config, ByteChatMLTokenizer(),
        compute_dtype=jnp.float32, eos_token_ids=[eos],
    )
    solo = gen2.generate_ids(prompts[0], SPEC)
    assert eos not in solo and len(solo) < SPEC.max_new_tokens
    engine = _spec_engine(gen2, kind)
    out = engine.submit(prompts[0], SPEC, timeout=240)
    assert out == solo
    assert eos not in out


def test_paged_variable_acceptance_across_block_boundaries(generator):
    """Small blocks + a long accepted stream: per-slot variable acceptance
    must advance write positions correctly across block boundaries (verify
    writes route through the block table; admission reserved K+1 positions
    of headroom past the budget)."""
    prompts = _prompts()
    cfg = GenerationConfig(
        max_new_tokens=24, do_sample=False, speculative_lookup=4
    )
    solo = generator.generate_ids(prompts[0], cfg)
    engine = _spec_engine(generator, "paged", block_len=8)
    reqs = []

    def ask(p):
        reqs.append(engine.submit_full(p, cfg, timeout=240))

    threads = [
        threading.Thread(target=ask, args=(p,)) for p in (prompts[0], prompts[1])
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    got = next(r for r in reqs if r.result is not None and r.prompt == prompts[0])
    assert got.result == solo
    # 24 accepted tokens at block_len=8 crossed >= 2 block boundaries with
    # speculation actually engaged (repetitive prompt -> drafts found)
    assert got.draft_tokens_proposed > 0
    assert 0 <= got.draft_tokens_accepted <= got.draft_tokens_proposed


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_per_request_telemetry_and_stats(generator, kind):
    """A speculative and a non-speculative request served concurrently:
    each reports its OWN draft counts (the non-spec one reports none), and
    the engine's ServingStats aggregate the totals."""
    prompts = _prompts()
    engine = _spec_engine(generator, kind)
    recs = {}

    def ask(tag, p, cfg):
        recs[tag] = engine.submit_full(p, cfg, timeout=240)

    threads = [
        threading.Thread(target=ask, args=("spec", prompts[0], SPEC)),
        threading.Thread(target=ask, args=("plain", prompts[2], GREEDY)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    spec, plain = recs["spec"], recs["plain"]
    assert spec.draft_tokens_proposed > 0
    assert 0 <= spec.draft_tokens_accepted <= spec.draft_tokens_proposed
    assert spec.spec_acceptance == (
        spec.draft_tokens_accepted / spec.draft_tokens_proposed
    )
    assert plain.draft_tokens_proposed == 0
    assert plain.spec_acceptance is None
    snap = engine.stats_snapshot()
    assert snap["draft_tokens_proposed"] >= spec.draft_tokens_proposed
    assert snap["draft_tokens_accepted"] >= spec.draft_tokens_accepted
    assert 0.0 <= snap["draft_acceptance_rate"] <= 1.0
    assert snap["mean_tokens_per_step"] > 0.0


@pytest.mark.parametrize("kind", ["continuous", "paged"])
def test_draft_model_self_draft_accepts_everything(generator, kind):
    """A draft model that IS the target proposes exactly the target's greedy
    choices: greedy verification accepts every draft (acceptance 1.0) and
    the output stays bit-identical to solo decode — the strongest equivalence
    check the draft-model path admits without a second checkpoint."""
    prompts = _prompts()
    gen2 = Generator(
        generator.params, generator.config, ByteChatMLTokenizer(),
        compute_dtype=jnp.float32, eos_token_ids=[],
        draft_params=generator.params, draft_config=generator.config,
    )
    cfg = GenerationConfig(
        max_new_tokens=12, do_sample=False, speculative_lookup=3
    )
    solo = generator.generate_ids(prompts[2], GREEDY)
    engine = _spec_engine(gen2, kind)
    # engine compiled with K=4; the request asks K=3 (drafts capped per slot)
    req = engine.submit_full(prompts[2], cfg, timeout=240)
    assert req.result == solo[: cfg.max_new_tokens]
    assert req.draft_tokens_proposed > 0
    assert req.draft_tokens_accepted == req.draft_tokens_proposed
    assert req.spec_acceptance == 1.0


def test_stream_rides_speculative_batch(generator):
    """engine.stream on a speculative engine surfaces the accepted runs as
    ordinary per-token stream events, totalling exactly the solo output."""
    prompts = _prompts()
    solo = generator.generate_ids(prompts[0], SPEC)
    engine = _spec_engine(generator, "continuous")
    got = list(engine.stream(prompts[0], SPEC, timeout=240))
    assert got == solo


def test_non_spec_engine_rejects_nothing_and_stays_plain(generator):
    """speculative_k=0 engines keep the plain one-token step: a request that
    asks for speculation still decodes correctly (drafting is simply off)."""
    prompts = _prompts()
    engine = ContinuousBatchingEngine(
        generator, slots=2, buf_len=96, prompt_bucket=16
    )
    out = engine.submit_full(prompts[0], SPEC, timeout=240)
    assert out.result == generator.generate_ids(prompts[0], GREEDY)
    assert out.draft_tokens_proposed == 0
