"""Ulysses (all-to-all sequence parallelism) must match plain XLA attention —
forward and gradients — since it is ordinary attention computed on a
head-sharded re-partition (SURVEY.md §5.7: the long-context capability the
reference lacks entirely; companion strategy to tests/test_ring_attention.py)."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_fine_tune_distributed_tpu.ops.attention import attention, xla_attention
from llm_fine_tune_distributed_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_attention_supported,
)


def _mesh(devs, data=1, fsdp=1, tensor=1, seq=4):
    shape = (data, fsdp, tensor, seq)
    n = data * fsdp * tensor * seq
    return Mesh(
        np.array(devs[:n]).reshape(shape), ("data", "fsdp", "tensor", "seq")
    )


def _qkv(b=2, s=64, h=8, kv=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, kv, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, kv, d), jnp.float32)
    return q, k, v


def test_ulysses_matches_xla_causal(eight_devices):
    mesh = _mesh(eight_devices, data=2, seq=4)
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda a, b_, c: ulysses_attention(a, b_, c, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_matches_xla_with_segments(eight_devices):
    """Packed rows through Ulysses: segment ids all-gather over the seq axis
    and the inner full-sequence kernel masks natively (packing x sequence
    parallelism, VERDICT r3 #5)."""
    from tests.test_ring_attention import _segments

    mesh = _mesh(eight_devices, data=2, seq=4)
    q, k, v = _qkv(b=2, s=32)
    seg = _segments(2, 32, pad_tail=4)
    ref = xla_attention(q, k, v, segment_ids=seg, causal=True)
    out = jax.jit(
        lambda a, b_, c, s_: ulysses_attention(a, b_, c, mesh=mesh, segment_ids=s_)
    )(q, k, v, seg)
    real = np.asarray(seg) > 0
    np.testing.assert_allclose(
        np.asarray(out)[real], np.asarray(ref)[real], atol=2e-5
    )


def test_ulysses_segment_gradients_match(eight_devices):
    from tests.test_ring_attention import _segments

    mesh = _mesh(eight_devices, seq=4)
    q, k, v = _qkv(b=2, s=32)
    seg = _segments(2, 32, pad_tail=4)
    w = (np.asarray(seg) > 0).astype(np.float32)[..., None, None]

    def loss_uly(q, k, v):
        return ((ulysses_attention(q, k, v, mesh=mesh, segment_ids=seg) * w) ** 2).sum()

    def loss_ref(q, k, v):
        return ((xla_attention(q, k, v, segment_ids=seg, causal=True) * w) ** 2).sum()

    g_uly = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_ulysses_matches_xla_with_padding(eight_devices):
    mesh = _mesh(eight_devices, data=2, seq=4)
    q, k, v = _qkv(b=2, s=32)
    pad = jnp.concatenate(
        [jnp.ones((2, 24), jnp.int32), jnp.zeros((2, 8), jnp.int32)], axis=1
    )
    ref = xla_attention(q, k, v, padding_mask=pad, causal=True)
    out = jax.jit(
        lambda a, b_, c, p: ulysses_attention(a, b_, c, mesh=mesh, padding_mask=p)
    )(q, k, v, pad)
    # pad-query rows are garbage in both impls; compare real tokens only
    real = np.asarray(pad, bool)
    np.testing.assert_allclose(
        np.asarray(out)[real], np.asarray(ref)[real], atol=2e-5
    )


def test_ulysses_with_tensor_axis(eight_devices):
    """Heads sharded over tensor simultaneously with the seq re-partition."""
    mesh = _mesh(eight_devices, tensor=2, seq=2, data=2)
    q, k, v = _qkv(b=2, s=32, h=8, kv=4)
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda a, b_, c: ulysses_attention(a, b_, c, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_gradients_match(eight_devices):
    mesh = _mesh(eight_devices, data=2, seq=4)
    q, k, v = _qkv(s=32)

    def loss_uly(q, k, v):
        return (ulysses_attention(q, k, v, mesh=mesh) ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_attention(q, k, v, causal=True) ** 2).sum()

    g_uly = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_dispatch_falls_back_without_mesh():
    q, k, v = _qkv(b=1, s=16)
    out = attention(q, k, v, impl="ulysses", mesh=None)  # no mesh -> xla path
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_supported_predicate(eight_devices):
    mesh = _mesh(eight_devices, data=2, seq=4)
    q, k, _ = _qkv(s=64)
    assert ulysses_attention_supported(q, k, mesh)
    assert not ulysses_attention_supported(q, k, None)
    assert not ulysses_attention_supported(q, k, mesh, sliding_window=8)
    q61 = jnp.zeros((2, 61, 8, 16))  # 61 not divisible by 4
    assert not ulysses_attention_supported(q61, k, mesh)
    # parallelism degree capped by kv heads: kv=2 local heads not divisible by 4
    k2 = jnp.zeros((2, 64, 2, 16))
    assert not ulysses_attention_supported(q, k2, mesh)


def test_model_forward_with_ulysses(eight_devices):
    """Full transformer forward, seq-sharded activations, ulysses attention ==
    unsharded xla forward. tiny has 4 heads / 2 kv heads -> seq degree 2."""
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params

    config = get_preset("tiny")
    mesh = _mesh(eight_devices, data=2, fsdp=2, seq=2)
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, config.vocab_size, (4, 64)), jnp.int32
    )

    ref, _ = forward(params, ids, config, attention_impl="xla", compute_dtype=jnp.float32)
    act = NamedSharding(mesh, P(("data", "fsdp"), "seq", None))
    from llm_fine_tune_distributed_tpu.parallel.diagnostics import assert_seq_parallel

    with assert_seq_parallel("ulysses"):
        out, _ = jax.jit(
            lambda p, i: forward(
                p,
                i,
                config,
                attention_impl="ulysses",
                compute_dtype=jnp.float32,
                activation_sharding=act,
            )
        )(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-4)


@pytest.mark.slow
def test_train_step_with_ulysses_matches_xla(eight_devices):
    """One full train step (grad-accum scan, freezing, AdamW) with
    seq-sharded activations + ulysses attention must produce the same loss
    and grad_norm as the unsharded XLA-attention step."""
    from llm_fine_tune_distributed_tpu.config import TrainConfig
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
    from llm_fine_tune_distributed_tpu.parallel.optimizer import build_optimizer
    from llm_fine_tune_distributed_tpu.train.state import TrainState
    from llm_fine_tune_distributed_tpu.train.step import build_train_step
    from llm_fine_tune_distributed_tpu.utils.tree import split_by_mask

    model_config = get_preset("tiny")

    def run(attention_impl, mesh, act_spec):
        train_config = TrainConfig(
            model_preset="tiny",
            per_device_batch_size=1,
            gradient_accumulation_steps=2,
            max_seq_length=64,
            gradient_checkpointing=True,
            attention_impl=attention_impl,
        )
        params = init_params(jax.random.PRNGKey(0), model_config, dtype=jnp.float32)
        mask = trainable_mask(params, model_config, train_config)
        trainable, frozen = split_by_mask(params, mask)
        optimizer = build_optimizer(train_config, None, total_steps=4, data_parallel_size=1)
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            trainable=trainable,
            frozen=frozen,
            opt_state=optimizer.init(trainable),
        )
        act = NamedSharding(mesh, act_spec) if mesh is not None else None
        step = jax.jit(build_train_step(model_config, train_config, optimizer, activation_sharding=act))
        rng = np.random.RandomState(1)
        batch = {
            "input_ids": jnp.asarray(rng.randint(0, model_config.vocab_size, (2, 4, 64)), jnp.int32),
            "loss_mask": jnp.ones((2, 4, 64), jnp.float32),
            "attention_mask": jnp.ones((2, 4, 64), jnp.int32),
        }
        _, metrics = step(state, batch)
        return float(metrics["loss"]), float(metrics["grad_norm"])

    from llm_fine_tune_distributed_tpu.parallel.diagnostics import assert_seq_parallel

    mesh = _mesh(eight_devices, data=2, fsdp=2, seq=2)
    loss_ref, gn_ref = run("xla", None, None)
    with assert_seq_parallel("ulysses"):
        loss_uly, gn_uly = run("ulysses", mesh, P(("data", "fsdp"), "seq", None))
    np.testing.assert_allclose(loss_uly, loss_ref, rtol=1e-4)
    np.testing.assert_allclose(gn_uly, gn_ref, rtol=1e-3)
