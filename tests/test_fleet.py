"""Fleet router + replica-set semantics (infer/fleet.py, infer/routing.py).

What this file pins, layer by layer:

- ``prefix_block_keys`` is the ONE prefix-key implementation: the paged
  engine's PrefixCache delegates to it, so router affinity and cache
  index can never drift;
- ``choose_replica`` is a pure function of (policy, views, rr_seq):
  prefix affinity wins, ties fall to least-loaded, load ties rotate, and
  degraded replicas never enter the candidate set;
- admission economics on scripted fake replicas: a 2-replica fleet with
  one idle replica NEVER 429s (the overflow reroutes), the fleet-wide
  429 carries Retry-After = the MINIMUM predicted drain across serving
  replicas, and total loss of replicas maps to the right taxonomy error;
- on the real tiny model: identical fleets fed the same request stream
  make identical placements (routing determinism), killing a replica
  mid-load sheds its queue to the survivor with zero hung waiters and
  greedy output bit-identical to solo ``generate_ids``, prefix affinity
  routes repeats back to the replica holding the cached blocks, and
  drain fans out across replicas.
"""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import (
    EngineFleet,
    GenerationConfig,
    Generator,
)
from llm_fine_tune_distributed_tpu.infer.engine import (
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.infer.errors import (
    DrainingError,
    NoHealthyReplicaError,
    QueueOverflowError,
    RetryableEngineError,
)
from llm_fine_tune_distributed_tpu.infer.paged import BlockAllocator, PrefixCache
from llm_fine_tune_distributed_tpu.infer.routing import (
    ROUTING_POLICIES,
    ReplicaView,
    choose_replica,
    prefix_block_keys,
)
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.observe.metrics import ServingStats

GREEDY = GenerationConfig(max_new_tokens=6, do_sample=False)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32, eos_token_ids=[]
    )


def _prompts():
    tok = ByteChatMLTokenizer()
    return [tok.encode(t) for t in ("alpha", "beta bravo", "the quick brown fox")]


def _fleet(generator, n=2, routing="prefix", **kw):
    """Fleet of fresh paged replicas with test-speed supervision, all
    wrapping the SAME generator (the shared-params property the fleet is
    built around)."""
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("restart_backoff_max_s", 0.02)
    return EngineFleet(
        [
            PagedContinuousBatchingEngine(
                generator, slots=4, buf_len=96, prompt_bucket=16,
                block_len=16, prefill_chunk=32, **kw,
            )
            for _ in range(n)
        ],
        routing=routing,
    )


# ------------------------------------------------- shared prefix-key helper


def test_prefix_block_keys_shared_with_prefix_cache():
    """PrefixCache.block_keys IS prefix_block_keys: same keys for the same
    prompt and block size, partial trailing block excluded, and keys are
    cumulative (key i embeds every token through block i)."""
    cache = PrefixCache(BlockAllocator(8), block_len=4)
    prompt = list(range(11))  # two full blocks + a 3-token tail
    keys = prefix_block_keys(prompt, 4)
    assert cache.block_keys(prompt) == keys
    assert len(keys) == 2  # the partial block gets NO key
    assert keys[1].startswith(keys[0])  # cumulative, exact-match bytes
    # one token changed inside block 0 changes EVERY key from there on
    other = prefix_block_keys([99] + prompt[1:], 4)
    assert other[0] != keys[0] and other[1] != keys[1]
    # shorter than one block -> no keys at all
    assert prefix_block_keys(prompt[:3], 4) == []


def test_prefix_block_keys_rejects_nonpositive_block_len():
    with pytest.raises(ValueError):
        prefix_block_keys([1, 2, 3], 0)
    with pytest.raises(ValueError):
        prefix_block_keys([1, 2, 3], -4)


def test_prefix_cache_resident_run_is_read_only():
    """resident_run counts leading cached keys without taking references
    or touching LRU order (a router probe must not pin blocks)."""
    alloc = BlockAllocator(8)
    cache = PrefixCache(alloc, block_len=2)
    keys = prefix_block_keys([1, 2, 3, 4, 5, 6], 2)
    blocks = alloc.alloc(2)
    cache.insert(keys[:2], blocks)
    before = {bid: alloc.refcount(bid) for bid in blocks}
    assert cache.resident_run(keys) == 2  # key 2 was never inserted
    assert cache.resident_run(keys[:1]) == 1
    assert cache.resident_run([b"missing"] + keys) == 0  # LEADING run only
    assert {bid: alloc.refcount(bid) for bid in blocks} == before


# ---------------------------------------------------- pure placement policy


def _views(**overrides):
    base = [
        ReplicaView(index=0, slots=4),
        ReplicaView(index=1, slots=4),
        ReplicaView(index=2, slots=4),
    ]
    for i, kw in overrides.items():
        for k, v in kw.items():
            setattr(base[int(i)], k, v)
    return base


def test_choose_replica_prefix_affinity_wins():
    views = _views(**{"1": {"prefix_hits": 3}, "2": {"prefix_hits": 1}})
    p = choose_replica("prefix", views)
    assert (p.index, p.reason) == (1, "prefix_affinity")
    # zero hits everywhere falls through to least-loaded
    p = choose_replica("prefix", _views(**{"0": {"queue_depth": 5}}))
    assert p.reason == "least_loaded" and p.index in (1, 2)


def test_choose_replica_least_loaded_uses_queue_and_slots():
    views = _views(
        **{
            "0": {"queue_depth": 2, "live_slots": 2},
            "1": {"queue_depth": 0, "live_slots": 3},
            "2": {"queue_depth": 4, "live_slots": 4},
        }
    )
    assert choose_replica("least-loaded", views).index == 1
    # prefix policy ignores affinity when scoring load-only candidates
    assert choose_replica("prefix", views).index == 1


def test_choose_replica_round_robin_rotates():
    views = _views()
    order = [choose_replica("round-robin", views, rr_seq=s).index for s in range(6)]
    assert order == [0, 1, 2, 0, 1, 2]
    assert choose_replica("round-robin", views, 1).reason == "round_robin"


def test_choose_replica_load_ties_rotate():
    """Equally idle replicas share first-touch traffic by rotation instead
    of piling onto replica 0."""
    views = _views()
    picks = {choose_replica("least-loaded", views, rr_seq=s).index for s in range(3)}
    assert picks == {0, 1, 2}


def test_choose_replica_excludes_degraded():
    views = _views(
        **{
            "0": {"healthy": False},
            "1": {"recovering": True},
            "2": {"draining": True},
        }
    )
    assert choose_replica("prefix", views) is None
    views[2].draining = False
    assert choose_replica("prefix", views).index == 2
    # pure function: same inputs, same answer, no hidden state
    assert choose_replica("prefix", views, 5) == choose_replica("prefix", views, 5)


def test_choose_replica_unknown_policy_raises():
    with pytest.raises(ValueError):
        choose_replica("random", _views())
    assert set(ROUTING_POLICIES) == {"prefix", "least-loaded", "round-robin"}


# ------------------------------------------- scripted-replica fleet dispatch


class _FakeResult:
    def __init__(self, result):
        self.result = result


class _FakeReplica:
    """The exact surface EngineFleet reads off a replica, with scripted
    failure behaviour — admission/failover economics without a device."""

    block_len = 0

    def __init__(self, index, slots=2, drain_s=1.0, raises=None):
        self.index = index
        self.slot_count = slots
        self.drain_s = drain_s
        self.raises = raises  # exception instance raised on every submit
        self.healthy = True
        self.draining = False
        self.recovering = False
        self.queue_depth = 0
        self.live_slots = 0
        self.calls = 0
        self.circuit_state = "closed"
        self.stats = ServingStats(slots=slots)  # fleet aggregation reads it

    def predicted_drain_s(self):
        return self.drain_s

    def prefix_match_len(self, keys):
        return 0

    def stats_snapshot(self):
        return {
            **self.stats.snapshot(),
            "circuit_state": self.circuit_state,
            "draining": self.draining,
        }

    def submit_full(self, prompt_ids, gen, seed=0, timeout=None):
        self.calls += 1
        if self.raises is not None:
            raise self.raises
        return _FakeResult(list(prompt_ids) + [self.index])


def test_idle_sibling_absorbs_overflow_never_429():
    """THE regression the fleet exists for: one saturated replica's 429
    reroutes to the idle sibling — the client never sees it."""
    full = _FakeReplica(0, raises=QueueOverflowError("full", retry_after_s=9.0))
    idle = _FakeReplica(1)
    # round-robin with rr_seq=0 targets the saturated replica FIRST
    fleet = EngineFleet([full, idle], routing="round-robin")
    out = fleet.submit([1, 2, 3], GREEDY, timeout=5)
    assert out == [1, 2, 3, 1]  # served by the sibling
    assert full.calls == 1 and idle.calls == 1
    snap_counters = fleet.stats_snapshot()
    assert snap_counters["requests_rerouted_overflow"] == 1
    assert snap_counters["requests_shed_fleet_saturated"] == 0


def test_all_saturated_429_quotes_minimum_drain():
    """Only when EVERY serving replica rejects does the fleet 429, and the
    Retry-After is the soonest ANY replica can absorb the retry — not
    whichever replica happened to reject last."""
    slow = _FakeReplica(0, drain_s=7.0,
                        raises=QueueOverflowError("full", retry_after_s=7.0))
    fast = _FakeReplica(1, drain_s=2.0,
                        raises=QueueOverflowError("full", retry_after_s=2.0))
    fleet = EngineFleet([slow, fast], routing="round-robin")
    with pytest.raises(QueueOverflowError) as ei:
        fleet.submit([1, 2, 3], GREEDY, timeout=5)
    assert ei.value.retry_after_s == 2.0
    assert slow.calls == 1 and fast.calls == 1  # each tried at most once
    assert fleet.stats_snapshot()["requests_shed_fleet_saturated"] == 1


def test_failover_resettles_on_sibling():
    dead = _FakeReplica(0, raises=RetryableEngineError("restart casualty"))
    ok = _FakeReplica(1)
    fleet = EngineFleet([dead, ok], routing="round-robin")
    assert fleet.submit([5], GREEDY) == [5, 1]
    assert fleet.stats_snapshot()["requests_failed_over"] == 1


def test_timeout_never_fails_over():
    """Client-deadline errors implicate the REQUEST, not the replica:
    replaying elsewhere would double the client's wait."""
    slow = _FakeReplica(0, raises=TimeoutError("deadline"))
    sibling = _FakeReplica(1)
    fleet = EngineFleet([slow, sibling], routing="round-robin")
    with pytest.raises(TimeoutError):
        fleet.submit([5], GREEDY, timeout=5)
    assert sibling.calls == 0


def test_all_replicas_terminal_maps_to_no_healthy_replica():
    fleet = EngineFleet([_FakeReplica(0), _FakeReplica(1)])
    for rep in fleet.replicas:
        rep.healthy = False
    with pytest.raises(NoHealthyReplicaError):
        fleet.submit([5], GREEDY)
    assert not fleet.healthy


def test_all_replicas_draining_maps_to_draining_error():
    fleet = EngineFleet([_FakeReplica(0), _FakeReplica(1)])
    for rep in fleet.replicas:
        rep.draining = True
    with pytest.raises(DrainingError):
        fleet.submit([5], GREEDY)
    assert fleet.draining


def test_all_replicas_recovering_is_retryable():
    fleet = EngineFleet([_FakeReplica(0), _FakeReplica(1)])
    for rep in fleet.replicas:
        rep.recovering = True
    with pytest.raises(RetryableEngineError) as ei:
        fleet.submit([5], GREEDY)
    assert ei.value.retry_after_s is not None  # min predicted drain


def test_router_intent_map_groups_same_prefix_bursts():
    """The intent map commits at DECISION time: with every replica cache
    still cold (prefix_match_len == 0 forever on the fakes), repeats of a
    routed prefix still follow the first placement."""
    reps = [_FakeReplica(0), _FakeReplica(1)]
    for rep in reps:
        rep.block_len = 4  # keys exist; caches never warm
    fleet = EngineFleet(reps, routing="prefix")
    a, b = [1, 2, 3, 4, 9], [7, 7, 7, 7, 9]
    fleet.submit(a, GREEDY)  # least-loaded tie, rotation -> replica 0
    fleet.submit(b, GREEDY)  # rotation -> replica 1
    for _ in range(3):
        fleet.submit(a, GREEDY)
        fleet.submit(b, GREEDY)
    placements = fleet.recent_placements()
    assert [i for i, _ in placements] == [0, 1, 0, 1, 0, 1, 0, 1]
    assert [r for _, r in placements[2:]] == ["prefix_affinity"] * 6
    snap = fleet.stats_snapshot()
    assert snap["requests_routed_prefix_affinity"] == 6
    assert snap["requests_routed_least_loaded"] == 2


def test_fleet_rejects_bad_config():
    with pytest.raises(ValueError):
        EngineFleet([], routing="prefix")
    with pytest.raises(ValueError):
        EngineFleet([_FakeReplica(0)], routing="hash-ring")


# ------------------------------------------------- real-model fleet behavior


def _settled(fleet, timeout_s=10.0):
    """Wait until no replica has queued or decoding work — so the next
    routing decision sees the same (idle) views on every run."""
    deadline = time.monotonic() + timeout_s
    while any(r.queue_depth or r.live_slots for r in fleet.replicas):
        assert time.monotonic() < deadline, "fleet never went idle"
        time.sleep(0.005)


def test_routing_determinism_same_stream_same_placements(generator):
    """Two identically built fleets fed the same sequential request stream
    place every request identically — placement is a pure function of the
    stream, not of timing."""
    tok = ByteChatMLTokenizer()
    stream = [
        tok.encode(t)
        for t in (
            # two fresh prefixes first (rotation spreads them), then
            # repeats and extensions (affinity follows the blocks)
            "the quick brown fox jumps over the lazy dog",
            "pack my box with five dozen liquor jugs",
            "the quick brown fox jumps over the sleeping cat",
            "pack my box with five dozen jars",
            "the quick brown fox jumps over the lazy dog again",
        )
    ]
    fleets = [_fleet(generator), _fleet(generator)]

    def run(fleet):
        outs = []
        for p in stream:
            _settled(fleet)  # sequential, settled stream: views reproducible
            outs.append(fleet.submit(p, GREEDY, timeout=240))
        return outs

    outs = [run(f) for f in fleets]
    assert outs[0] == outs[1]
    placements = [f.recent_placements() for f in fleets]
    assert placements[0] == placements[1]
    # and the stream actually exercised both replicas and both reasons
    assert {i for i, _ in placements[0]} == {0, 1}
    assert "prefix_affinity" in {r for _, r in placements[0]}
    # greedy decode through the fleet is bit-identical to solo decode
    solo = [generator.generate_ids(p, GREEDY) for p in stream]
    assert outs[0] == solo


def test_prefix_affinity_follows_replica_cache(generator):
    """With the intent map disabled, affinity is driven purely by the
    replicas' REAL prefix caches: a repeat routes back to the replica that
    prefilled the blocks, and reads them as a cache hit."""
    tok = ByteChatMLTokenizer()
    prompt = tok.encode("the quick brown fox jumps over the lazy dog")
    fleet = _fleet(generator)
    fleet._prefix_cap = 0  # kill the intent map; only real residency scores
    first = fleet.submit(prompt, GREEDY, timeout=240)
    home = fleet.recent_placements()[0][0]
    for _ in range(2):
        assert fleet.submit(prompt, GREEDY, timeout=240) == first
    placements = fleet.recent_placements()
    assert [i for i, _ in placements] == [home] * 3
    assert [r for _, r in placements[1:]] == ["prefix_affinity"] * 2
    snap = fleet.stats_snapshot()
    assert snap["prefix_tokens_reused"] > 0
    assert snap["per_replica"][str(home)]["prefix_tokens_reused"] > 0
    assert snap["per_replica"][str(1 - home)]["prefix_tokens_reused"] == 0


def test_replica_crash_sheds_queue_to_survivor(generator):
    """Kill one replica mid-load (terminal: circuit threshold 1): its
    queued requests resettle on the sibling, every waiter resolves, and
    every greedy result is bit-identical to solo ``generate_ids``. The
    fleet stays healthy on the survivor."""
    prompts = _prompts()
    solo = [generator.generate_ids(p, GREEDY) for p in prompts]
    fleet = _fleet(generator, routing="round-robin",
                   circuit_threshold=1, circuit_window_s=60.0)
    victim, survivor = fleet.replicas
    # first decode tick on the victim dies, and keeps dying if it restarts
    victim.faults.fail_decode_next(10)

    outcomes = [None] * len(prompts)

    def ask(i):
        try:
            outcomes[i] = ("ok", fleet.submit(prompts[i], GREEDY, timeout=240))
        except BaseException as e:  # noqa: BLE001 - recording outcome
            outcomes[i] = ("err", e)

    threads = [threading.Thread(target=ask, args=(i,)) for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    assert all(not t.is_alive() for t in threads), "a waiter hung"
    assert [o[0] for o in outcomes] == ["ok"] * len(prompts), outcomes
    assert [o[1] for o in outcomes] == solo  # bit-identical despite the crash
    # zero hung waiters on EITHER replica's settle ledger
    assert victim._pending == 0 and survivor._pending == 0
    assert not victim.healthy and survivor.healthy and fleet.healthy
    assert fleet.circuit_state == "closed"  # fleet view: still serving
    snap = fleet.stats_snapshot()
    assert snap["requests_failed_over"] >= 1
    assert snap["healthy_replicas"] == 1
    # the victim stays out of the candidate set for NEW work
    fleet.submit(prompts[0], GREEDY, timeout=240)
    assert fleet.recent_placements()[-1][0] == fleet.replicas.index(survivor)


def test_fleet_drain_fans_out(generator):
    fleet = _fleet(generator)
    prompts = _prompts()
    assert fleet.submit(prompts[0], GREEDY, timeout=240) is not None  # warm
    fleet.begin_drain()
    assert fleet.draining
    with pytest.raises(DrainingError):
        fleet.submit(prompts[1], GREEDY, timeout=5)
    assert fleet.wait_drained(timeout_s=30.0)


def test_fleet_stats_aggregate_math(generator):
    """Counters sum, generation is the max, rates are recomputed from the
    summed counters, and merged histogram counts equal the per-replica
    totals (exact merge, same fixed buckets)."""
    fleet = _fleet(generator)
    prompts = _prompts()
    for p in prompts:
        fleet.submit(p, GREEDY, timeout=240)
    snap = fleet.stats_snapshot()
    per = snap["per_replica"]
    assert set(per) == {"0", "1"}
    for key in ("tokens_served", "requests_completed", "prompt_tokens"):
        assert snap[key] == per["0"][key] + per["1"][key]
    assert snap["tokens_served"] == len(prompts) * GREEDY.max_new_tokens
    assert snap["slots"] == per["0"]["slots"] + per["1"]["slots"]
    assert snap["engine_generation"] == max(
        per["0"]["engine_generation"], per["1"]["engine_generation"]
    )
    assert snap["histograms"]["ttft_s"]["count"] == (
        per["0"]["histograms"]["ttft_s"]["count"]
        + per["1"]["histograms"]["ttft_s"]["count"]
    )
    assert snap["replicas"] == 2 and snap["routing"] == "prefix"
    assert snap["healthy_replicas"] == 2 and snap["available_replicas"] == 2
    total_routed = sum(
        snap[k]
        for k in (
            "requests_routed_prefix_affinity",
            "requests_routed_least_loaded",
            "requests_routed_round_robin",
        )
    )
    assert total_routed == len(prompts)


# ------------------------------------------------- adapter-affinity routing


def test_choose_replica_adapter_affinity_outranks_prefix():
    """A replica holding the request's LoRA adapter wins even against a
    deeper prompt-prefix run elsewhere (an adapter miss pays a disk
    hot-load and can evict a neighbor tenant's slot — the costlier miss);
    prefix depth then breaks ties WITHIN the adapter-resident set."""
    views = _views(
        **{"1": {"prefix_hits": 3}, "2": {"adapter_hits": 1}}
    )
    p = choose_replica("prefix", views)
    assert (p.index, p.reason) == (2, "adapter_affinity")
    # within the adapter-resident set, prefix depth still orders candidates
    views = _views(
        **{
            "0": {"adapter_hits": 1},
            "1": {"adapter_hits": 1, "prefix_hits": 2},
            "2": {"prefix_hits": 5},
        }
    )
    p = choose_replica("prefix", views)
    assert (p.index, p.reason) == (1, "adapter_affinity")
    # adapter_hits never enter the other policies
    p = choose_replica("least-loaded", _views(**{"2": {"adapter_hits": 1}}))
    assert p.reason == "least_loaded"


def test_fleet_routes_tenant_back_to_adapter_resident_replica(
    generator, tmp_path
):
    """End-to-end adapter affinity: the tenant's FIRST request hot-loads
    the adapter on whichever replica wins the load tie; every later
    request for that tenant routes back to the SAME replica (reason
    "adapter_affinity"), so one fleet-wide load serves the tenant's whole
    stream — and the fleet snapshot's per-tenant map shows the merged
    token count."""
    from llm_fine_tune_distributed_tpu.config import TrainConfig
    from llm_fine_tune_distributed_tpu.infer.adapters import AdapterRegistry
    from llm_fine_tune_distributed_tpu.parallel.lora import (
        add_lora_params,
        save_lora_adapter,
    )

    base = generator.params
    params = add_lora_params(base, jax.random.PRNGKey(7), rank=4, alpha=8.0)

    def bump(node):
        if isinstance(node, dict):
            if "lora_b" in node:
                node = dict(node)
                node["lora_b"] = jnp.ones_like(node["lora_b"]) * 0.01
                return node
            return {k: bump(v) for k, v in node.items()}
        return node

    save_lora_adapter(
        bump(params), str(tmp_path / "acme"),
        TrainConfig(freeze_strategy="lora", lora_rank=4, lora_alpha=8.0),
    )
    fleet = EngineFleet(
        [
            PagedContinuousBatchingEngine(
                generator, slots=4, buf_len=96, prompt_bucket=16,
                block_len=16, prefill_chunk=32,
                restart_backoff_s=0.01, restart_backoff_max_s=0.02,
                adapters=AdapterRegistry(
                    base, str(tmp_path), max_adapters=4
                ),
            )
            for _ in range(2)
        ],
        routing="prefix",
    )
    prompts = _prompts()
    fleet.submit(prompts[0], GREEDY, timeout=240, adapter="acme")
    home = [
        i for i, rep in enumerate(fleet.replicas)
        if rep.adapter_resident("acme")
    ]
    assert len(home) == 1  # exactly one replica paid the load
    for p in prompts[1:]:
        fleet.submit(p, GREEDY, timeout=240, adapter="acme")
    # repeats routed home: still one resident copy, affinity counted
    assert [
        i for i, rep in enumerate(fleet.replicas)
        if rep.adapter_resident("acme")
    ] == home
    placements = fleet.recent_placements()
    assert placements[0][1] in ("least_loaded", "prefix_affinity")
    assert all(r == "adapter_affinity" for _, r in placements[1:])
    snap = fleet.stats_snapshot()
    assert snap["requests_routed_adapter_affinity"] == len(prompts) - 1
    assert snap["per_tenant"]["acme"]["requests"] == len(prompts)
    assert (
        snap["per_tenant"]["acme"]["tokens"]
        == len(prompts) * GREEDY.max_new_tokens
    )
    # base-model requests (no adapter) never see adapter affinity
    fleet.submit(prompts[0], GREEDY, timeout=240)
    assert fleet.recent_placements()[-1][1] != "adapter_affinity"
