"""Sequence packing (packing=True): exactness tests.

The load-bearing property: a packed row must produce IDENTICAL per-token
logits/losses to running each example alone — the segment mask and
per-segment positions make packing an exact transformation, not an
approximation."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig
from llm_fine_tune_distributed_tpu.data.dataset import (
    build_sft_arrays,
    format_chat_example,
    tokenize_example,
)
from llm_fine_tune_distributed_tpu.data.packing import (
    build_packed_sft_arrays,
    pack_examples,
    packing_efficiency,
)
from llm_fine_tune_distributed_tpu.data.tokenizer import load_tokenizer
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params

SYS = "You are a helpful expert."
SEQ = 256


def _rows(n):
    return [
        {"full-question": f"q {i}?", "answer": f"answer {i} " + "word " * (3 + i % 5)}
        for i in range(n)
    ]


@pytest.fixture(scope="module")
def tok():
    return load_tokenizer("byte-chatml")


def test_pack_examples_layout(tok):
    examples = [
        tokenize_example(
            format_chat_example(r, SYS)["messages"], tok, SEQ
        )
        for r in _rows(8)
    ]
    packed = pack_examples(examples, SEQ)
    n_rows = packed["input_ids"].shape[0]
    assert n_rows < 8, "short examples should share rows"
    # segment ids increase from 1 within a row; 0 marks the padding tail
    for r in range(n_rows):
        seg = packed["segment_ids"][r]
        real = seg > 0
        assert packed["attention_mask"][r][real].all()
        assert not packed["attention_mask"][r][~real].any()
        segs = np.unique(seg[real])
        assert (segs == np.arange(1, len(segs) + 1)).all()
        # positions restart at each segment
        for sid in segs:
            pos = packed["positions"][r][seg == sid]
            assert (pos == np.arange(len(pos))).all()
    # total real tokens preserved
    assert packed["attention_mask"].sum() == sum(e.length for e in examples)
    assert 0.0 < packing_efficiency(packed) <= 1.0


@pytest.mark.slow
def test_packed_forward_matches_individual(tok):
    """Logits of each packed segment == logits of the example run alone."""
    config = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    rows = _rows(5)
    examples = [
        tokenize_example(format_chat_example(r, SYS)["messages"], tok, SEQ)
        for r in rows
    ]
    packed = pack_examples(examples, SEQ)

    packed_logits, _ = forward(
        params,
        jnp.asarray(packed["input_ids"]),
        config,
        padding_mask=jnp.asarray(packed["attention_mask"]),
        segment_ids=jnp.asarray(packed["segment_ids"]),
        positions=jnp.asarray(packed["positions"]),
        compute_dtype=jnp.float32,
        logits_dtype=jnp.float32,
    )
    packed_logits = np.asarray(packed_logits)

    # reconstruct per-example logits from the packed rows
    seg_cursor = {}
    for r in range(packed["input_ids"].shape[0]):
        seg = packed["segment_ids"][r]
        for sid in np.unique(seg[seg > 0]):
            idx = np.where(seg == sid)[0]
            seg_cursor[(r, sid)] = packed_logits[r, idx]

    # order of (row, sid) follows first-fit insertion order == example order
    flat_packed = []
    rows_used = packed["segment_ids"]
    order = []
    for r in range(rows_used.shape[0]):
        for sid in np.unique(rows_used[r][rows_used[r] > 0]):
            order.append((r, sid))
    # map each example to its (row, sid) by matching tokens
    for ex in examples:
        ln = ex.length
        ids = jnp.asarray(ex.input_ids[None, :ln])
        solo, _ = forward(
            params, ids, config, compute_dtype=jnp.float32, logits_dtype=jnp.float32
        )
        solo = np.asarray(solo)[0]
        # find the matching packed segment by token equality
        match = None
        for key, logits_seg in seg_cursor.items():
            r, sid = key
            idx = np.where(packed["segment_ids"][r] == sid)[0]
            if len(idx) == ln and (packed["input_ids"][r, idx] == ex.input_ids[:ln]).all():
                match = logits_seg
                break
        assert match is not None, "packed segment not found for example"
        np.testing.assert_allclose(match, solo, rtol=2e-4, atol=2e-4)


def test_packed_arrays_loss_mask_never_crosses_segments(tok):
    packed = build_packed_sft_arrays(_rows(12), tok, SEQ, system_prompt=SYS)
    seg = packed["segment_ids"]
    lm = packed["loss_mask"]
    # wherever a new segment starts (seg changes and is > 0), loss_mask is 0:
    # predicting a segment's first token from the previous segment is invalid
    starts = (seg[:, 1:] != seg[:, :-1]) & (seg[:, 1:] > 0)
    assert (lm[:, 1:][starts] == 0).all()


@pytest.mark.slow
def test_packed_training_with_seq_axis_matches_flat(tmp_path, eight_devices):
    """packing x sequence parallelism (VERDICT r3 #5): a packed train step on
    a live seq axis (ring and ulysses) computes the SAME loss as the flat-mesh
    XLA-attention step — same data, same seed, same init."""
    from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    jsonl = tmp_path / "qa.jsonl"
    with open(jsonl, "w") as f:
        for i in range(64):
            f.write(json.dumps({
                "topic": "Knots",
                "question": f"question {i}?",
                "answer": f"answer {i}: " + "word " * (3 + i % 6),
            }) + "\n")
    convert_jsonl_to_parquet(str(jsonl), str(tmp_path / "qa_dataset.parquet"), verbose=False)

    def make(out, attention_impl, mesh):
        return TrainConfig(
            model_name="tiny-random",
            model_preset="tiny",
            tokenizer_path="byte-chatml",
            system_prompt=SYS,
            data_dir=str(tmp_path),
            dataset_file="qa_dataset.parquet",
            output_dir=str(out),
            packing=True,
            per_device_batch_size=2,
            gradient_accumulation_steps=2,
            max_seq_length=256,
            mesh=mesh,
            attention_impl=attention_impl,
            use_native_loader=False,
        )

    def one_step_loss(cfg):
        trainer = SFTTrainer(cfg)
        batch = next(iter(trainer.loader.epoch(0)))
        dev = trainer._device_batch(batch, trainer._batch_sharding, local_shards=True)
        _, metrics = trainer.train_step(trainer.state, dev)
        return float(metrics["loss"])

    ref = one_step_loss(
        make(tmp_path / "flat", "xla", MeshConfig(data=1, fsdp=2, tensor=1, seq=1))
    )
    from llm_fine_tune_distributed_tpu.parallel.diagnostics import assert_seq_parallel

    with assert_seq_parallel("ring"):
        ring = one_step_loss(
            make(tmp_path / "ring", "ring", MeshConfig(data=1, fsdp=2, tensor=1, seq=2))
        )
    with assert_seq_parallel("ulysses"):
        uly = one_step_loss(
            make(tmp_path / "uly", "ulysses", MeshConfig(data=1, fsdp=2, tensor=1, seq=2))
        )
    assert abs(ring - ref) < 2e-3, (ring, ref)
    assert abs(uly - ref) < 2e-3, (uly, ref)


@pytest.mark.slow
def test_packed_sft_end_to_end(tmp_path):
    from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    jsonl = tmp_path / "qa.jsonl"
    with open(jsonl, "w") as f:
        for i in range(96):
            f.write(json.dumps({
                "topic": "Knots",
                "question": f"question {i}?",
                "answer": f"answer {i}: " + "word " * (3 + i % 6),
            }) + "\n")
    convert_jsonl_to_parquet(str(jsonl), str(tmp_path / "qa_dataset.parquet"), verbose=False)

    def make(packing, out):
        return TrainConfig(
            model_name="tiny-random",
            model_preset="tiny",
            tokenizer_path="byte-chatml",
            system_prompt=SYS,
            data_dir=str(tmp_path),
            dataset_file="qa_dataset.parquet",
            output_dir=str(out),
            packing=packing,
            epochs=2,
            per_device_batch_size=2,
            gradient_accumulation_steps=2,
            learning_rate=2e-3,
            max_seq_length=256,
            eval_steps=4,
            logging_steps=2,
            save_steps=100,
            mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=1),
            use_native_loader=False,
        )

    packed_trainer = SFTTrainer(make(True, tmp_path / "packed"))
    unpacked_steps = 96 * 9 // 10 // (2 * 2 * 2)  # examples / global batch
    assert packed_trainer.steps_per_epoch < unpacked_steps, (
        packed_trainer.steps_per_epoch, unpacked_steps
    )
    packed_trainer.train()
    losses = [h["loss"] for h in packed_trainer.metrics.history if "loss" in h]
    assert losses[-1] < losses[0], f"packed loss did not decrease: {losses}"
    evals = [h["eval_loss"] for h in packed_trainer.metrics.history if "eval_loss" in h]
    assert evals, "packed eval never ran"
    assert (tmp_path / "packed" / "best_model" / "model.safetensors").exists()
