"""Ring attention (sequence parallelism) must match plain XLA attention —
forward and gradients — since it is the same math rearranged around a
ppermute ring (SURVEY.md §5.7: the long-context capability the reference
lacks entirely)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_fine_tune_distributed_tpu.ops.attention import attention, xla_attention
from llm_fine_tune_distributed_tpu.parallel.ring_attention import (
    ring_attention,
    ring_attention_supported,
)


def _mesh(devs, data=1, fsdp=1, tensor=1, seq=8):
    shape = (data, fsdp, tensor, seq)
    n = data * fsdp * tensor * seq
    return Mesh(
        np.array(devs[:n]).reshape(shape), ("data", "fsdp", "tensor", "seq")
    )


def _qkv(b=2, s=64, h=4, kv=2, d=16, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, kv, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, kv, d), jnp.float32)
    return q, k, v


def test_ring_matches_xla_causal(eight_devices):
    mesh = _mesh(eight_devices, seq=8)
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda a, b_, c: ring_attention(a, b_, c, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_matches_xla_with_padding(eight_devices):
    mesh = _mesh(eight_devices, seq=4, data=2)
    q, k, v = _qkv(b=2, s=32)
    pad = jnp.concatenate(
        [jnp.ones((2, 24), jnp.int32), jnp.zeros((2, 8), jnp.int32)], axis=1
    )
    ref = xla_attention(q, k, v, padding_mask=pad, causal=True)
    out = jax.jit(
        lambda a, b_, c, p: ring_attention(a, b_, c, mesh=mesh, padding_mask=p)
    )(q, k, v, pad)
    # pad-query rows are garbage in both impls; compare real tokens only
    real = np.asarray(pad, bool)
    np.testing.assert_allclose(
        np.asarray(out)[real], np.asarray(ref)[real], atol=2e-5
    )


def test_ring_with_tensor_axis(eight_devices):
    """Heads sharded over tensor simultaneously with seq over the ring."""
    mesh = _mesh(eight_devices, tensor=2, seq=4)
    q, k, v = _qkv(b=2, s=32, h=4, kv=2)
    ref = xla_attention(q, k, v, causal=True)
    out = jax.jit(lambda a, b_, c: ring_attention(a, b_, c, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gradients_match(eight_devices):
    mesh = _mesh(eight_devices, seq=8)
    q, k, v = _qkv(s=32)

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh=mesh) ** 2).sum()

    def loss_ref(q, k, v):
        return (xla_attention(q, k, v, causal=True) ** 2).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def _segments(b, s, seed=0, n_seg=3, pad_tail=8):
    """Contiguous per-row segment ids like data/packing.py produces:
    1..n_seg blocks then a 0 pad tail."""
    rng = np.random.RandomState(seed)
    out = np.zeros((b, s), np.int32)
    for r in range(b):
        cuts = np.sort(rng.choice(np.arange(1, s - pad_tail), n_seg - 1, replace=False))
        bounds = [0, *cuts.tolist(), s - pad_tail]
        for i in range(n_seg):
            out[r, bounds[i] : bounds[i + 1]] = i + 1
    return jnp.asarray(out)


def test_ring_matches_xla_with_segments(eight_devices):
    """Packed rows (block-diagonal causal via segment ids) through the ring:
    the rotated key-side id chunk must reproduce xla_attention's segment
    masking exactly (packing x sequence parallelism, VERDICT r3 #5)."""
    mesh = _mesh(eight_devices, seq=8)
    q, k, v = _qkv(s=64)
    seg = _segments(2, 64)
    ref = xla_attention(q, k, v, segment_ids=seg, causal=True)
    out = jax.jit(
        lambda a, b_, c, s_: ring_attention(a, b_, c, mesh=mesh, segment_ids=s_)
    )(q, k, v, seg)
    real = np.asarray(seg) > 0  # pad-tail rows are garbage in both impls
    np.testing.assert_allclose(
        np.asarray(out)[real], np.asarray(ref)[real], atol=2e-5
    )


def test_ring_segments_via_dispatch(eight_devices):
    """attention(impl='ring', segment_ids=...) keeps the seq axis (no
    fallback) and matches the xla reference."""
    mesh = _mesh(eight_devices, seq=4, data=2)
    q, k, v = _qkv(b=2, s=32)
    seg = _segments(2, 32, pad_tail=4)
    ref = xla_attention(q, k, v, segment_ids=seg, causal=True)
    from llm_fine_tune_distributed_tpu.parallel.diagnostics import assert_seq_parallel

    with assert_seq_parallel("ring"):
        out = jax.jit(
            lambda a, b_, c, s_: attention(
                a, b_, c, impl="ring", mesh=mesh, segment_ids=s_
            )
        )(q, k, v, seg)
    real = np.asarray(seg) > 0
    np.testing.assert_allclose(
        np.asarray(out)[real], np.asarray(ref)[real], atol=2e-5
    )


def test_ring_segment_gradients_match(eight_devices):
    mesh = _mesh(eight_devices, seq=8)
    q, k, v = _qkv(s=32)
    seg = _segments(2, 32, pad_tail=4)
    w = (np.asarray(seg) > 0).astype(np.float32)[..., None, None]

    def loss_ring(q, k, v):
        return ((ring_attention(q, k, v, mesh=mesh, segment_ids=seg) * w) ** 2).sum()

    def loss_ref(q, k, v):
        return ((xla_attention(q, k, v, segment_ids=seg, causal=True) * w) ** 2).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_dispatch_falls_back_without_mesh():
    q, k, v = _qkv(b=1, s=16)
    out = attention(q, k, v, impl="ring", mesh=None)  # no mesh -> xla path
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_supported_predicate(eight_devices):
    mesh = _mesh(eight_devices, seq=8)
    q, k, _ = _qkv(s=64)
    assert ring_attention_supported(q, k, mesh)
    assert not ring_attention_supported(q, k, None)
    assert not ring_attention_supported(q, k, mesh, sliding_window=8)
    q61 = jnp.zeros((2, 61, 4, 16))  # 61 not divisible by 8
    assert not ring_attention_supported(q61, k, mesh)


def test_model_forward_with_ring(eight_devices):
    """Full transformer forward, seq-sharded activations, ring attention ==
    unsharded xla forward."""
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params

    config = get_preset("tiny")
    mesh = _mesh(eight_devices, data=2, seq=4)
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, config.vocab_size, (2, 64)), jnp.int32
    )

    ref, _ = forward(params, ids, config, attention_impl="xla", compute_dtype=jnp.float32)
    act = NamedSharding(mesh, P(("data", "fsdp"), "seq", None))
    from llm_fine_tune_distributed_tpu.parallel.diagnostics import assert_seq_parallel

    with assert_seq_parallel("ring"):
        out, _ = jax.jit(
            lambda p, i: forward(
                p,
                i,
                config,
                attention_impl="ring",
                compute_dtype=jnp.float32,
                activation_sharding=act,
            )
        )(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-4)
