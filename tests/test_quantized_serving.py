"""Quantized serving (ISSUE 12): int8 paged-KV pool with per-block absmax
scales, weight-only int8/NF4 serving params, and the fused Pallas paged
decode-attention kernel.

Pinned contracts:

- per-block KV quantization round-trips within tolerance under the
  engine's copy-on-write discipline (a shared block is written by exactly
  one prefill; sharers write only their divergent suffix), the null block
  0 stays all-zero no matter what is scattered at it, and a prefix block
  shared by many tables dequantizes bit-identically for every sharer;
- the paged engine over an int8 pool emits exactly solo generate_ids'
  greedy tokens — the same bit-parity headline the bf16 pool pins — and
  keeps doing so with speculation (K>0) and across preempt/resume;
- the fused kernel (pl.pallas_call(interpret=True) in tier-1) matches the
  XLA gather+dequant reference to f32 resolution; the compiled TPU path
  rides the slow marker; off-TPU the engine defaults to the XLA fallback;
- memory accounting: the int8 pool halves KV bytes/token, the breakdown
  (weight_bytes / kv_pool_bytes / kv_scale_bytes / bytes_saved_vs_bf16)
  adds up, and the serving gauges expose weight/KV residency.
"""

import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import (
    init_paged_cache,
    init_params,
)
from llm_fine_tune_distributed_tpu.ops.flash_attention import (
    paged_decode_attention,
    paged_decode_mode,
)
from llm_fine_tune_distributed_tpu.ops.int8 import (
    dequantize_kv_gather,
    maybe_quantize,
    quantize_kv_write,
)

GREEDY = GenerationConfig(max_new_tokens=8, do_sample=False)
SAMPLED = GenerationConfig(max_new_tokens=6, do_sample=True, temperature=1.0)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32,
        eos_token_ids=[],
    )


@pytest.fixture(scope="module")
def int8_generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        maybe_quantize(params, "int8"), mc, ByteChatMLTokenizer(),
        compute_dtype=jnp.float32, eos_token_ids=[],
    )


def _paged(generator, **kw):
    return PagedContinuousBatchingEngine(
        generator, slots=4, buf_len=96, prompt_bucket=16,
        block_len=16, prefill_chunk=32, **kw,
    )


def _enc(text):
    return ByteChatMLTokenizer().encode(text)


def _prompts():
    return [_enc(t) for t in ("alpha", "beta bravo", "the quick brown fox")]


# --------------------------------------------------- per-block KV quant unit


def _empty_pool(num_blocks=8, block_len=8, heads=2, head_dim=16):
    codes = jnp.zeros((num_blocks, block_len, heads, head_dim), jnp.int8)
    scales = jnp.zeros((num_blocks, heads), jnp.float32)
    return codes, scales


def test_kv_write_roundtrip_and_scale_placement():
    """One prefill writes two blocks of one row; a sharer then writes only
    its divergent suffix into a third block (the COW discipline). Content
    round-trips within int8 tolerance and scales land per (block, head)."""
    rng = np.random.default_rng(0)
    codes, scales = _empty_pool()
    x0 = jnp.asarray(rng.normal(size=(1, 16, 2, 16)), jnp.float32)
    blk0 = jnp.asarray([[1] * 8 + [2] * 8], jnp.int32)
    off0 = jnp.asarray([list(range(8)) * 2], jnp.int32)
    codes, scales = quantize_kv_write(codes, scales, blk0, off0, x0)
    assert scales.shape == (8, 2)  # one absmax per (block, kv head)
    # blocks 1 and 2 carry exactly the per-block absmax of what was written
    w = np.asarray(x0[0]).reshape(2, 8, 2, 16)
    expect = np.abs(w).max(axis=(1, 3))
    np.testing.assert_allclose(np.asarray(scales)[1:3], expect, rtol=1e-6)
    assert float(jnp.abs(scales[3:]).max()) == 0.0

    # the sharer appends its suffix (2 tokens) into its own block 4
    x1 = jnp.asarray(rng.normal(size=(1, 2, 2, 16)), jnp.float32)
    codes, scales = quantize_kv_write(
        codes, scales, jnp.asarray([[4, 4]], jnp.int32),
        jnp.asarray([[0, 1]], jnp.int32), x1,
    )

    tables = jnp.asarray([[1, 2, 0], [1, 4, 0]], jnp.int32)
    got = np.asarray(dequantize_kv_gather(codes, scales, tables, jnp.float32))
    ref0 = np.asarray(x0[0])
    err = np.abs(got[0, :16] - ref0).max() / np.abs(ref0).max()
    assert err < 0.01  # int8 per-block absmax resolution
    err1 = np.abs(got[1, 8:10] - np.asarray(x1[0])).max()
    assert err1 < 0.01 * float(jnp.abs(x1).max())
    # the shared prefix block dequantizes IDENTICALLY for both sharers
    np.testing.assert_array_equal(got[0, :8], got[1, :8])
    # table positions past the allocation (null block) gather exact zeros
    assert np.abs(got[:, 16:]).max() == 0.0


def test_kv_null_block_zero_stays_zero():
    """Scatters redirected at block 0 (the engine's clip-redirect target
    for out-of-range writes) must not leave residue: codes and scales of
    the null block stay zero, so every table's padding reads as zeros."""
    codes, scales = _empty_pool()
    x = jnp.full((1, 4, 2, 16), 7.5, jnp.float32)
    codes, scales = quantize_kv_write(
        codes, scales, jnp.asarray([[0, 0, 1, 1]], jnp.int32),
        jnp.asarray([[0, 1, 0, 1]], jnp.int32), x,
    )
    assert int(jnp.abs(codes[0]).max()) == 0
    assert float(jnp.abs(scales[0]).max()) == 0.0
    # the legitimate block-1 write landed normally
    assert float(scales[1].min()) > 0.0


def test_kv_scale_growth_rescales_resident_codes():
    """A later, larger-magnitude write into a half-full block grows the
    block scale; the already-resident codes are re-quantized under the new
    scale so earlier content still dequantizes correctly."""
    codes, scales = _empty_pool()
    small = jnp.full((1, 4, 2, 16), 0.1, jnp.float32)
    codes, scales = quantize_kv_write(
        codes, scales, jnp.full((1, 4), 3, jnp.int32),
        jnp.arange(4, dtype=jnp.int32)[None], small,
    )
    big = jnp.full((1, 4, 2, 16), 10.0, jnp.float32)
    codes, scales = quantize_kv_write(
        codes, scales, jnp.full((1, 4), 3, jnp.int32),
        (4 + jnp.arange(4, dtype=jnp.int32))[None], big,
    )
    assert float(scales[3].min()) == 10.0
    got = np.asarray(
        dequantize_kv_gather(codes, scales, jnp.asarray([[3]], jnp.int32),
                             jnp.float32)
    )[0]
    # the early tokens survived the rescale (1 int8 step of 10/127 ~ 0.079)
    np.testing.assert_allclose(got[:4], 0.1, atol=10.0 / 127 + 1e-6)
    np.testing.assert_allclose(got[4:], 10.0, atol=10.0 / 127 + 1e-6)


def test_init_paged_cache_int8_layout_and_validation():
    mc = get_preset("tiny")
    cache = init_paged_cache(mc, num_blocks=6, block_len=8, kv_quant="int8")
    entry = cache["layers"]["0"]
    assert entry["k"].dtype == jnp.int8 and entry["v"].dtype == jnp.int8
    assert entry["k"].shape[:3] == (6, 8, mc.num_kv_heads)
    # one scale per (block, kv head), riding the same block ids as the pool
    assert entry["k_scale"].shape == (6, mc.num_kv_heads)
    assert entry["k_scale"].dtype == jnp.float32
    with pytest.raises(ValueError, match="kv_quant"):
        init_paged_cache(mc, num_blocks=6, block_len=8, kv_quant="int4")


# ------------------------------------------------------------ engine parity


def test_paged_int8_kv_greedy_parity_with_live_neighbors(generator):
    """Greedy over the int8 pool, with sampled neighbors mutating the same
    pool, emits exactly solo generate_ids' tokens (the bf16 pool's
    headline guarantee carried over to the quantized layout)."""
    eng = _paged(generator, kv_quant="int8")
    prompts = _prompts()
    solo = [generator.generate_ids(p, GREEDY) for p in prompts]
    done = []
    sampled = [
        threading.Thread(
            target=lambda s=s: eng.submit(_enc("noise maker"), SAMPLED, seed=s)
        )
        for s in range(2)
    ]
    for t in sampled:
        t.start()
    outs = [eng.submit(p, GREEDY) for p in prompts]
    for t in sampled:
        t.join()
    assert outs == solo


def test_paged_int8_kv_speculative_parity(generator):
    """Speculative verify (K>0) writes K+1 positions per tick through the
    quantized scatter and rolls back rejected tokens by pointer math only —
    greedy output stays bit-identical to solo."""
    eng = _paged(generator, kv_quant="int8", speculative_k=3)
    prompts = _prompts()
    solo = [generator.generate_ids(p, GREEDY) for p in prompts]
    outs = [eng.submit(p, GREEDY) for p in prompts]
    assert outs == solo


def test_dense_int8_weights_greedy_parity(int8_generator):
    """Weight-only int8 serving on the DENSE engine: the slot batch emits
    exactly what solo generate_ids produces over the same quantized
    params, and the breakdown reports the weight savings."""
    eng = ContinuousBatchingEngine(
        int8_generator, slots=2, buf_len=96, prompt_bucket=16,
    )
    prompts = _prompts()
    solo = [int8_generator.generate_ids(p, GREEDY) for p in prompts]
    outs = [eng.submit(p, GREEDY) for p in prompts]
    assert outs == solo
    mem = eng.memory_breakdown()
    assert mem["bytes_saved_vs_bf16"] > 0


def test_paged_int8_weights_and_kv_parity(int8_generator):
    """The full quantized stack — int8 weights AND int8 KV pool — on the
    paged engine keeps the engine-vs-solo bit-parity."""
    eng = _paged(int8_generator, kv_quant="int8")
    prompts = _prompts()
    solo = [int8_generator.generate_ids(p, GREEDY) for p in prompts]
    outs = [eng.submit(p, GREEDY) for p in prompts]
    assert outs == solo


def test_preempt_resume_over_quantized_pool(generator):
    """A best_effort greedy victim preempted by an interactive arrival and
    resumed from banked blocks emits the uninterrupted run's tokens — the
    banked blocks live in the int8 pool and re-dequantize on resume."""
    eng = PagedContinuousBatchingEngine(
        generator, slots=2, buf_len=256, prompt_bucket=64,
        block_len=16, prefill_chunk=256, kv_quant="int8",
    )
    victim_cfg = GenerationConfig(max_new_tokens=48, do_sample=False)
    prompt = _enc("a forty-ish token victim prompt for block banking")
    solo = generator.generate_ids(prompt, victim_cfg)
    # warm the programs/buckets this dance touches
    eng.submit(prompt, victim_cfg, priority="best_effort", timeout=240)
    eng.submit(_enc("interactive warm"), SAMPLED, seed=3, timeout=240)
    eng.submit(_enc("x" * 70), GREEDY, timeout=240)
    eng.mark_compile_warm()

    occupier = threading.Thread(
        target=lambda: eng.submit(
            _enc("long sampled occupier"),
            GenerationConfig(max_new_tokens=64, do_sample=True,
                             temperature=1.0),
            seed=9, timeout=240,
        )
    )
    occupier.start()
    deadline = time.monotonic() + 120
    while eng.live_slots < 1:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    stream = eng.stream(prompt, victim_cfg, priority="best_effort",
                        timeout=240)
    tokens = [next(stream), next(stream)]  # victim is decoding now
    trigger_result = []
    trigger = threading.Thread(
        target=lambda: trigger_result.append(
            eng.submit(
                _enc("interactive arrival"),
                GenerationConfig(max_new_tokens=8, do_sample=True,
                                 temperature=1.0),
                seed=4, timeout=240,
            )
        )
    )
    trigger.start()
    tokens.extend(stream)
    trigger.join()
    occupier.join()
    snap = eng.stats_snapshot()
    assert snap["preemptions"] >= 1
    assert tokens == solo


# ------------------------------------------------------------- fused kernel


def _kernel_case(seed=0, b=2, hkv=2, groups=2, d=16, block_len=16,
                 num_blocks=8, nb=3):
    rng = np.random.default_rng(seed)
    codes, scales = _empty_pool(num_blocks, block_len, hkv, d)
    vcodes, vscales = _empty_pool(num_blocks, block_len, hkv, d)
    lengths = np.asarray([block_len * 2 + 5, block_len + 3], np.int32)
    tables = jnp.asarray([[1, 2, 3], [4, 5, 0]], jnp.int32)
    for row in range(b):
        n = int(lengths[row])
        x = jnp.asarray(rng.normal(size=(1, n, hkv, d)), jnp.float32)
        y = jnp.asarray(rng.normal(size=(1, n, hkv, d)), jnp.float32)
        blk = tables[row][jnp.arange(n) // block_len][None]
        off = (jnp.arange(n) % block_len)[None]
        codes, scales = quantize_kv_write(codes, scales, blk, off, x)
        vcodes, vscales = quantize_kv_write(vcodes, vscales, blk, off, y)
    q = jnp.asarray(rng.normal(size=(b, 1, hkv * groups, d)), jnp.float32)
    return q, codes, vcodes, scales, vscales, tables, jnp.asarray(lengths)


def _xla_reference(q, ck, cv, ks, vs, tables, lengths):
    """The default fallback path: gather+dequant then masked attention."""
    b, _, hq, d = q.shape
    k = dequantize_kv_gather(ck, ks, tables, jnp.float32)
    v = dequantize_kv_gather(cv, vs, tables, jnp.float32)
    groups = hq // k.shape[2]
    k = jnp.repeat(k, groups, axis=2)
    v = jnp.repeat(v, groups, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k)
    logits = logits * (float(d) ** -0.5)
    mask = jnp.arange(k.shape[1])[None, :] < lengths[:, None]
    logits = jnp.where(mask[:, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def test_fused_kernel_interpret_matches_xla_reference():
    """pl.pallas_call(interpret=True): the fused gather+dequant+online-
    softmax kernel reproduces the XLA reference to f32 resolution,
    including rows whose tables end in null-block padding."""
    q, ck, cv, ks, vs, tables, lengths = _kernel_case()
    got = paged_decode_attention(
        q, ck, cv, ks, vs, tables, lengths=lengths, interpret=True,
    )
    ref = _xla_reference(q, ck, cv, ks, vs, tables, lengths)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=1e-5, atol=1e-5,
    )


def test_fused_kernel_single_head_no_groups():
    """Degenerate GQA (hq == hkv) exercises the groups=1 reshape path."""
    q, ck, cv, ks, vs, tables, lengths = _kernel_case(seed=1, groups=1)
    got = paged_decode_attention(
        q, ck, cv, ks, vs, tables, lengths=lengths, interpret=True,
    )
    ref = _xla_reference(q, ck, cv, ks, vs, tables, lengths)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=1e-5, atol=1e-5,
    )


def test_paged_decode_mode_defaults_and_env(monkeypatch):
    """Off-TPU the engine must default to the XLA fallback (zero Pallas in
    CPU tier-1 decode); PAGED_DECODE overrides for the gated head-to-head."""
    monkeypatch.delenv("PAGED_DECODE", raising=False)
    if jax.default_backend() != "tpu":
        assert paged_decode_mode() == "xla"
    monkeypatch.setenv("PAGED_DECODE", "fused")
    assert paged_decode_mode() == "fused"
    monkeypatch.setenv("PAGED_DECODE", "interpret")
    assert paged_decode_mode() == "interpret"
    monkeypatch.setenv("PAGED_DECODE", "xla")
    assert paged_decode_mode() == "xla"


def test_engine_parity_through_interpreted_fused_kernel(generator,
                                                        monkeypatch):
    """End-to-end: the paged engine decoding THROUGH the fused kernel
    (interpret mode) emits exactly solo generate_ids' greedy tokens."""
    monkeypatch.setenv("PAGED_DECODE", "interpret")
    eng = _paged(generator, kv_quant="int8")
    prompts = _prompts()
    solo = [generator.generate_ids(p, GREEDY) for p in prompts]
    outs = [eng.submit(p, GREEDY) for p in prompts]
    assert outs == solo


@pytest.mark.slow
def test_fused_kernel_compiled_tpu():
    """The compiled Mosaic kernel (TPU only): same contract as interpret
    mode, run head-to-head against the XLA reference on device."""
    if jax.default_backend() != "tpu":
        pytest.skip("compiled Pallas path needs a TPU backend")
    q, ck, cv, ks, vs, tables, lengths = _kernel_case()
    got = paged_decode_attention(
        q, ck, cv, ks, vs, tables, lengths=lengths, interpret=False,
    )
    ref = _xla_reference(q, ck, cv, ks, vs, tables, lengths)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2,  # bf16 MXU accumulation vs f32 reference
    )


# --------------------------------------------------------- memory accounting


def test_memory_breakdown_halves_kv_and_exposes_gauges(generator):
    bf16 = _paged(generator)
    q = _paged(generator, kv_quant="int8")
    # one request through each so the worker thread has built its pool
    bf16.submit(_enc("warm"), GREEDY)
    q.submit(_enc("warm"), GREEDY)
    mb, mq = bf16.memory_breakdown(), q.memory_breakdown()
    # same pool geometry: the f32 test pool stores 4 bytes/elem, int8 one
    assert mq["kv_pool_bytes"] * 4 == mb["kv_pool_bytes"]
    assert mq["kv_scale_bytes"] > 0 and mb["kv_scale_bytes"] == 0
    # unquantized residency saves nothing; int8 KV saves pool-minus-scales
    # against the bf16 logical layout
    assert mb["bytes_saved_vs_bf16"] == 0
    assert mq["bytes_saved_vs_bf16"] == (
        mq["kv_pool_bytes"] - mq["kv_scale_bytes"]
    )
    snap = q.stats_snapshot()
    assert snap["weight_bytes"] == mq["weight_bytes"] > 0
    assert snap["kv_pool_bytes"] == mq["kv_pool_bytes"] > 0
