"""Block-paged KV engine (infer/engine.py PagedContinuousBatchingEngine +
infer/paged.py): allocator/refcount mechanics, paged-vs-solo greedy
bit-parity under live sampled neighbors, shared-prefix reuse, chunked
prefill equivalence, and block-pool admission control. Same contracts as
the dense engine (tests/test_engine.py) — only the KV layout changed."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.engine import PagedContinuousBatchingEngine
from llm_fine_tune_distributed_tpu.infer.paged import (
    NULL_BLOCK,
    BlockAllocator,
    PrefixCache,
)
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params

GREEDY = GenerationConfig(max_new_tokens=6, do_sample=False)
SAMPLED = GenerationConfig(max_new_tokens=6, do_sample=True, temperature=1.0)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32, eos_token_ids=[]
    )


@pytest.fixture()
def engine(generator):
    return PagedContinuousBatchingEngine(
        generator, slots=4, buf_len=96, prompt_bucket=16,
        block_len=16, prefill_chunk=32,
    )


def _prompts():
    tok = ByteChatMLTokenizer()
    return [tok.encode(t) for t in ("alpha", "beta bravo", "the quick brown fox")]


# ------------------------------------------------------------- allocator unit


def test_allocator_alloc_free_refcount():
    a = BlockAllocator(8)  # 1 null + 7 usable
    assert a.free_count == 7 and a.used_count == 0
    blocks = a.alloc(3)
    assert len(blocks) == 3 and NULL_BLOCK not in blocks
    assert a.used_count == 3
    assert all(a.refcount(b) == 1 for b in blocks)
    a.ref(blocks[0])
    assert a.refcount(blocks[0]) == 2
    a.free(blocks[0])
    assert a.refcount(blocks[0]) == 1 and a.used_count == 3  # still held
    for b in blocks:
        a.free(b)
    assert a.used_count == 0 and a.free_count == 7
    # all-or-nothing: asking for more than free leaves the pool untouched
    assert a.alloc(8) is None
    assert a.free_count == 7


def test_allocator_guards_null_and_unallocated():
    a = BlockAllocator(4)
    with pytest.raises(ValueError):
        a.ref(NULL_BLOCK)
    with pytest.raises(ValueError):
        a.free(NULL_BLOCK)
    with pytest.raises(ValueError):
        a.ref(2)  # never allocated
    with pytest.raises(ValueError):
        BlockAllocator(1)  # no usable block


def test_prefix_cache_match_insert_evict_refcounts():
    a = BlockAllocator(8)
    cache = PrefixCache(a, block_len=4)
    prompt = list(range(11))  # 2 full blocks + a 3-token tail
    keys = cache.block_keys(prompt)
    assert len(keys) == 2
    blocks = a.alloc(2)
    cache.insert(keys, blocks)  # cache takes its own refs
    assert all(a.refcount(b) == 2 for b in blocks)
    # a prompt agreeing on block 0 but not block 1 matches exactly one block
    other = prompt[:4] + [99] * 7
    hit = cache.match(cache.block_keys(other), limit=2)
    assert hit == blocks[:1]
    assert a.refcount(blocks[0]) == 3  # caller now holds one too
    a.free(blocks[0])
    # limit caps the run even on a full match
    assert cache.match(keys, limit=1) == blocks[:1]
    a.free(blocks[0])
    # owner retires: blocks survive on the cache's refs alone
    for b in blocks:
        a.free(b)
    assert a.used_count == 2
    # eviction drops LRU entries until enough blocks are free
    dropped = cache.evict(want_free=a.free_count + 2)
    assert dropped == 2 and a.used_count == 0 and len(cache) == 0


# ----------------------------------------------------------- decode contracts


def test_paged_greedy_bit_identical_to_solo_with_live_neighbors(generator, engine):
    """The headline guarantee carried over from the dense engine: a greedy
    request decoding against the BLOCK POOL, with live sampled neighbors
    mutating that same pool, produces exactly solo generate_ids' tokens."""
    prompts = _prompts()
    solo = [generator.generate_ids(p, GREEDY) for p in prompts]

    long_cfg = GenerationConfig(max_new_tokens=48, do_sample=True, temperature=1.0)
    results = [None] * len(prompts)

    def occupy():
        engine.submit(prompts[0], long_cfg, seed=11, timeout=240)

    def run(i):
        results[i] = engine.submit(prompts[i], GREEDY, seed=0, timeout=240)

    occ = threading.Thread(target=occupy)
    occ.start()
    workers = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    occ.join()
    for i, r in enumerate(results):
        assert r == solo[i], f"prompt {i}: {r} != solo {solo[i]}"


def test_paged_sampled_deterministic_in_request_seed(generator):
    """Sampled output depends only on (request, seed) — not on slot index,
    co-residents, or block placement (fresh engine per run so the second
    submission lands in different blocks via the prefix cache)."""
    prompt = _prompts()[2]
    runs = []
    for _ in range(2):
        eng = PagedContinuousBatchingEngine(
            generator, slots=4, buf_len=96, prompt_bucket=16,
            block_len=16, prefill_chunk=32,
        )
        runs.append(eng.submit(prompt, SAMPLED, seed=7, timeout=240))
    assert runs[0] == runs[1]


def test_prefix_cache_reuses_shared_prompt_blocks(generator, engine):
    """Second request with the same long prompt prefills only the suffix:
    the leading full blocks come from the prefix cache, output unchanged."""
    tok = ByteChatMLTokenizer()
    prompt = tok.encode("wilderness system prompt " * 3)  # > 2 full blocks
    solo = generator.generate_ids(prompt, GREEDY)

    first = engine.submit(prompt, GREEDY, timeout=240)
    before = engine.stats_snapshot()
    second = engine.submit(prompt, GREEDY, timeout=240)
    after = engine.stats_snapshot()

    assert first == solo and second == solo
    full_blocks = len(prompt) // 16
    assert full_blocks >= 2
    reused = after["prefix_tokens_reused"] - before["prefix_tokens_reused"]
    # every full block reuses, except the last when the prompt length is an
    # exact block multiple (>= 1 suffix token must prefill for the logits)
    assert reused >= (full_blocks - 1) * 16 and reused > 0
    assert after["prefix_hit_rate"] > 0
    assert after["prefix_cache_blocks"] >= full_blocks - 1


def test_chunked_prefill_matches_solo(generator, engine):
    """A prompt longer than prefill_chunk ingests in several bounded chunks
    (interleaved with neighbors' decode) yet yields solo's exact tokens."""
    tok = ByteChatMLTokenizer()
    prompt = tok.encode(
        "a long prompt spanning several bounded prefill chunks for decode steps"
    )
    # 3 chunks at prefill_chunk=32, with room for max_new_tokens in buf_len=96
    assert 2 * 32 < len(prompt) <= 96 - GREEDY.max_new_tokens
    solo = generator.generate_ids(prompt, GREEDY)

    results = [None, None]

    def neighbor():
        results[1] = engine.submit(
            _prompts()[0],
            GenerationConfig(max_new_tokens=24, do_sample=True, temperature=1.0),
            seed=3, timeout=240,
        )

    t = threading.Thread(target=neighbor)
    t.start()
    results[0] = engine.submit(prompt, GREEDY, timeout=240)
    t.join()
    assert results[0] == solo
    snap = engine.stats_snapshot()
    assert snap["prefill_chunks"] >= 3


# ----------------------------------------------------------- admission control


def test_pool_oom_request_rejected_when_it_can_never_fit(generator):
    """A request whose block need exceeds the whole pool errors immediately
    (waiting would deadlock the FIFO head forever)."""
    eng = PagedContinuousBatchingEngine(
        generator, slots=2, buf_len=96, prompt_bucket=16,
        block_len=16, prefill_chunk=32, num_blocks=3,  # 2 usable blocks
    )
    big = GenerationConfig(max_new_tokens=80, do_sample=False)  # needs 6 blocks
    with pytest.raises(ValueError, match="blocks"):
        eng.submit(_prompts()[0], big, timeout=240)
    # pool untouched after the rejection
    assert eng._allocator.used_count == 0


def test_pool_contention_head_waits_then_completes(generator):
    """When blocks run out the FIFO head WAITS (nothing overtakes it) and
    admits once the running request retires — both outputs exact."""
    tok = ByteChatMLTokenizer()
    prompt = tok.encode("the quick brown fox")  # ~2 blocks at L=16
    cfg = GenerationConfig(max_new_tokens=10, do_sample=False)
    eng = PagedContinuousBatchingEngine(
        generator, slots=2, buf_len=96, prompt_bucket=16,
        block_len=16, prefill_chunk=64, num_blocks=4,  # 3 usable: one req's worth
    )
    solo = eng._generator.generate_ids(prompt, cfg)
    results = [None, None]

    def run(i):
        results[i] = eng.submit(prompt, cfg, timeout=240)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert results[0] == solo and results[1] == solo


def test_release_returns_blocks_and_stats_report_pool(generator, engine):
    """After traffic drains, the only blocks still held belong to the prefix
    cache; the stats snapshot carries the pool gauges the bench emits."""
    for p in _prompts():
        engine.submit(p, GREEDY, timeout=240)
    snap = engine.stats_snapshot()
    assert snap["total_blocks"] == engine._allocator.num_blocks - 1
    assert snap["blocks_in_use"] == snap["prefix_cache_blocks"]
    assert 0 <= snap["block_pool_occupancy"] <= 1
    assert snap["peak_blocks_in_use"] >= snap["blocks_in_use"]
    assert snap["requests_completed"] == 3
