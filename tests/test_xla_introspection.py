"""XLA runtime introspection (observe/xla.py) and its wiring.

What this file pins, layer by layer:

- ``CompileLedger``: dedup by (program, shapes), re-record bumps the
  count, the ``mark_warm()`` boundary turns every later record into a
  ``recompiles_after_warmup`` tick with a listener notification, and
  ``merge`` unions DISTINCT ledgers (fleet replicas sharing one
  Generator share one ledger object — identity dedup);
- ``instrument()``: first call registers with the ledger (AOT path with
  cost analysis, or plain-jit wall timing), later calls don't re-record,
  and outputs are identical either way;
- utilization math: ``utilization_from_cost`` clamps to [0, 1] and
  returns 0.0 on unknowns; ``device_peak_specs`` honors env overrides;
- the zero-recompile acceptance gate: both slot engines driven through
  mixed traffic (speculative K, two LoRA adapters, prefix hits AND
  misses, an injected crash + recovery), warm-marked, then the SAME
  traffic again — no hot-path program may compile post-warmup;
- fleet trace propagation: one RequestTrace spans the router decision,
  a failed hop, and the completing replica — scripted and real;
- ``ProfilerCapture``: one capture at a time (busy rejection), auto-stop,
  flight-recorder events.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import (
    EngineFleet,
    GenerationConfig,
    Generator,
)
from llm_fine_tune_distributed_tpu.infer.engine import (
    ContinuousBatchingEngine,
    PagedContinuousBatchingEngine,
)
from llm_fine_tune_distributed_tpu.infer.errors import RetryableEngineError
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.observe.tracing import RequestTrace
from llm_fine_tune_distributed_tpu.observe.xla import (
    CaptureBusyError,
    CompileLedger,
    ProfilerCapture,
    annotate,
    device_peak_specs,
    instrument,
    utilization_from_cost,
)

GREEDY = GenerationConfig(max_new_tokens=6, do_sample=False)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32, eos_token_ids=[]
    )


def _prompts():
    tok = ByteChatMLTokenizer()
    return [tok.encode(t) for t in ("alpha", "beta bravo", "the quick brown fox")]


# --------------------------------------------------------- compile ledger


def test_ledger_dedup_by_program_and_shapes():
    led = CompileLedger()
    led.record("slot_step", "(4, 96)", 0.5)
    led.record("slot_step", "(4, 96)", 0.25)  # cache rebuild, same sig
    led.record("slot_step", "(8, 96)", 0.1)  # new shape bucket
    led.record("paged_step", "(4, 96)", 0.2)
    snap = led.snapshot()
    assert snap["programs"]["slot_step"]["compiles"] == 3
    assert snap["programs"]["slot_step"]["compile_s"] == pytest.approx(0.85)
    assert snap["programs"]["paged_step"]["compiles"] == 1
    assert snap["total_compiles"] == 4
    assert snap["total_compile_s"] == pytest.approx(1.05)
    assert snap["recompiles_after_warmup"] == 0
    assert snap["warmed"] is False


def test_ledger_warmup_boundary_counts_and_notifies():
    led = CompileLedger()
    seen = []
    led.add_listener(lambda prog, sig, dt, gen: seen.append((prog, sig, gen)))
    led.record("slot_step", "(4,)", 0.1)
    assert seen == []  # pre-warm compiles are expected, not events
    led.mark_warm()
    assert led.warmed
    led.current_generation = 3
    led.record("slot_step", "(8,)", 0.2)  # NEW shape after warm: still a bug
    led.record("slot_step", "(4,)", 0.05)  # rebuild of a known sig: also
    snap = led.snapshot()
    assert snap["recompiles_after_warmup"] == 2
    assert seen == [("slot_step", "(8,)", 3), ("slot_step", "(4,)", 3)]
    # a broken listener never breaks a record
    led.add_listener(lambda *a: (_ for _ in ()).throw(RuntimeError("x")))
    led.record("slot_step", "(16,)", 0.01)
    assert led.snapshot()["recompiles_after_warmup"] == 3


def test_ledger_merge_dedups_shared_ledgers():
    shared = CompileLedger()
    shared.record("paged_step", "(4,)", 1.0)
    other = CompileLedger()
    other.record("paged_step", "(4,)", 0.5)
    other.mark_warm()
    # two replicas sharing one Generator present the SAME ledger twice
    merged = CompileLedger.merge([shared, shared, other, None])
    assert merged["programs"]["paged_step"]["compiles"] == 2  # not 3
    assert merged["total_compile_s"] == pytest.approx(1.5)
    assert merged["warmed"] is False  # all must be warm
    shared.mark_warm()
    assert CompileLedger.merge(iter([shared, other]))["warmed"] is True
    empty = CompileLedger.merge([])
    assert empty["total_compiles"] == 0 and empty["warmed"] is False


def test_ledger_cost_for_prefers_most_recent():
    led = CompileLedger()
    led.record("slot_step", "(4,)", 0.1, flops=100.0, bytes_accessed=10.0)
    led.record("spec_slot_step", "(4,)", 0.1, flops=300.0, bytes_accessed=30.0)
    led.record("draft_slot_step", "(4,)", 0.1, flops=999.0, bytes_accessed=99.0)
    assert led.cost_for(("slot_step", "spec_slot_step")) == (300.0, 30.0)
    assert led.cost_for(("missing",)) == (0.0, 0.0)
    no_cost = CompileLedger()
    no_cost.record("slot_step", "(4,)", 0.1)  # no cost analysis attached
    assert no_cost.cost_for(("slot_step",)) == (0.0, 0.0)


def test_utilization_from_cost_clamps_and_zeroes():
    mfu, bw = utilization_from_cost(5e12, 5e11, 0.01, 1e15, 1e14)
    assert mfu == pytest.approx(0.5)
    assert bw == pytest.approx(0.5)
    # faster-than-roofline measurements clamp instead of reporting >100%
    assert utilization_from_cost(1e18, 1e18, 0.01, 1e12, 1e12) == (1.0, 1.0)
    # any unknown input -> 0.0, never a division error
    assert utilization_from_cost(0.0, 0.0, 0.01, 1e12, 1e12) == (0.0, 0.0)
    assert utilization_from_cost(1e12, 1e12, 0.0, 1e12, 1e12) == (0.0, 0.0)
    assert utilization_from_cost(1e12, 1e12, 0.01, 0.0, 0.0) == (0.0, 0.0)


def test_device_peak_specs_env_override(monkeypatch):
    monkeypatch.setenv("SERVE_PEAK_FLOPS", "2e14")
    monkeypatch.setenv("SERVE_PEAK_HBM_BPS", "8e11")
    assert device_peak_specs() == (2e14, 8e11)
    monkeypatch.delenv("SERVE_PEAK_FLOPS")
    monkeypatch.delenv("SERVE_PEAK_HBM_BPS")
    # CPU test runs have no TPU roofline: (0, 0), not an invented peak
    assert device_peak_specs() == (0.0, 0.0)


# ------------------------------------------------------------- instrument


@pytest.mark.parametrize("aot", [True, False])
def test_instrument_records_once_and_preserves_output(aot):
    led = CompileLedger()
    fn = jax.jit(lambda x: x * 2 + 1)
    x = jnp.arange(8, dtype=jnp.float32)
    wrapped = instrument("double", fn, led, aot=aot)
    first = wrapped(x)
    assert jnp.array_equal(first, fn(x))
    for _ in range(3):  # steady state: no re-records
        assert jnp.array_equal(wrapped(x), first)
    snap = led.snapshot()
    assert snap["programs"]["double"]["compiles"] == 1
    assert snap["programs"]["double"]["compile_s"] > 0.0
    if aot:  # the AOT path attaches cost analysis
        flops, nbytes = led.cost_for(("double",))
        assert nbytes > 0.0


def test_instrument_aot_falls_back_on_unlowerable_fn():
    led = CompileLedger()
    wrapped = instrument("plain", lambda x: x + 1, led, aot=True)  # no .lower
    assert wrapped(41) == 42
    assert wrapped(1) == 2
    assert led.snapshot()["programs"]["plain"]["compiles"] == 1


def test_annotate_is_a_usable_context():
    with annotate("admit"):
        pass  # TraceAnnotation or nullcontext — either must just work


# ------------------------------------------- zero-recompile acceptance gate


def test_zero_recompile_guard_mixed_traffic(generator, tmp_path):
    """THE gate: a fresh Generator's engines are driven through every hot
    path — paged prefix miss + hit, speculative drafting, dense decode
    under two LoRA adapters and the base model, and a crash + recovery on
    each engine — then warm-marked; the identical traffic replayed must
    not compile a single new program, and the ledger is visible in both
    engines' ``stats_snapshot()``."""
    from llm_fine_tune_distributed_tpu.config import TrainConfig
    from llm_fine_tune_distributed_tpu.infer.adapters import AdapterRegistry
    from llm_fine_tune_distributed_tpu.parallel.lora import (
        add_lora_params,
        save_lora_adapter,
    )

    mc = get_preset("tiny")
    # fresh Generator: its ledger's warm mark must not leak into (or from)
    # the module fixture's shared jit caches
    gen = Generator(
        generator.params, mc, ByteChatMLTokenizer(),
        compute_dtype=jnp.float32, eos_token_ids=[],
    )
    for i, name in enumerate(("acme", "globex")):
        lora = add_lora_params(
            generator.params, jax.random.PRNGKey(20 + i), rank=4, alpha=8.0
        )
        save_lora_adapter(
            lora, str(tmp_path / name),
            TrainConfig(freeze_strategy="lora", lora_rank=4, lora_alpha=8.0),
        )
    kw = dict(
        slots=4, buf_len=96, prompt_bucket=16,
        restart_backoff_s=0.01, restart_backoff_max_s=0.02,
    )
    dense = ContinuousBatchingEngine(
        gen, adapters=AdapterRegistry(
            generator.params, str(tmp_path), max_adapters=4
        ), **kw,
    )
    paged = PagedContinuousBatchingEngine(
        gen, block_len=16, prefill_chunk=32, speculative_k=2, **kw,
    )
    assert dense.compile_ledger is paged.compile_ledger  # shared Generator

    tok = ByteChatMLTokenizer()
    prefix = tok.encode("the quick brown fox jumps over the lazy dog")
    spec_gen = GenerationConfig(
        max_new_tokens=8, do_sample=False, speculative_lookup=2
    )
    rep_prompt = tok.encode("ab") * 8  # repetitive: prompt-lookup fires
    prompts = _prompts()

    def traffic():
        paged.submit(prefix + tok.encode(" one"), GREEDY, timeout=240)
        paged.submit(prefix + tok.encode(" two"), GREEDY, timeout=240)  # hit
        paged.submit(rep_prompt, spec_gen, timeout=240)  # fused draft/verify
        dense.submit(prompts[0], GREEDY, timeout=240, adapter="acme")
        dense.submit(prompts[1], GREEDY, timeout=240, adapter="globex")
        dense.submit(prompts[2], GREEDY, timeout=240)  # base model
        for engine in (paged, dense):  # crash + recovery per engine
            engine.faults.fail_decode_next(1)
            with pytest.raises(RetryableEngineError):
                engine.submit(prompts[0], GREEDY, timeout=60)
            assert engine.submit(prompts[0], GREEDY, timeout=240) is not None

    # two warmup passes: pass 1 compiles the cold-cache shapes (prefix
    # misses, first prefills), pass 2 the warm-cache shapes (deeper
    # resident runs shorten the suffix prefill) — after it, a third
    # identical pass can need nothing new
    traffic()
    traffic()
    warm = paged.stats_snapshot()["compile"]
    assert warm["total_compiles"] > 0 and not warm["warmed"]
    # speculative_k on the paged engine routes EVERY tick through the
    # fused draft/verify program, so plain paged_step never compiles
    assert {"spec_paged_step", "paged_final", "slot_step"} <= set(
        warm["programs"]
    )
    paged.mark_compile_warm()  # one shared ledger: marks both engines
    traffic()  # steady state: same shapes, same programs, zero compiles

    for engine in (paged, dense):
        snap = engine.stats_snapshot()
        comp = snap["compile"]
        assert comp["warmed"] is True
        assert comp["recompiles_after_warmup"] == 0, comp
        assert comp["total_compiles"] == warm["total_compiles"]
        # utilization gauges ride the same snapshot (0.0 on CPU: no
        # roofline to measure against, never an invented number)
        assert 0.0 <= snap["model_flops_utilization"] <= 1.0
        assert 0.0 <= snap["hbm_bandwidth_utilization"] <= 1.0
    # post-warmup recompiles would also be on the flight-recorder timeline
    assert not [
        e for e in paged.recorder.events() if e["kind"] == "recompile"
    ]


# ------------------------------------------------ fleet trace propagation


class _FakeResult:
    def __init__(self, result, trace=None):
        self.result = result
        self.trace = trace


class _TracingReplica:
    """Scripted replica that OPTS IN to trace adoption — the surface a real
    engine presents to the fleet's trace propagation."""

    SUPPORTS_TRACE = True
    block_len = 0

    def __init__(self, index, raises=None):
        self.index = index
        self.slot_count = 2
        self.raises = raises
        self.healthy = True
        self.draining = False
        self.recovering = False
        self.queue_depth = 0
        self.live_slots = 0
        self.circuit_state = "closed"
        self.seen_trace = None

    def predicted_drain_s(self):
        return 1.0

    def prefix_match_len(self, keys):
        return 0

    def submit_full(self, prompt_ids, gen, seed=0, timeout=None, trace=None):
        self.seen_trace = trace
        if self.raises is not None:
            raise self.raises
        if trace is not None:
            trace.request_id = 1
            trace.mark("completed")
        return _FakeResult(list(prompt_ids) + [self.index], trace=trace)


def test_fleet_failover_is_one_trace():
    """A scripted failover produces ONE trace: the router's decision span
    for the first placement, the failover span naming the error, the
    second decision span, and the sibling's completion — all under one
    trace id."""
    dead = _TracingReplica(0, raises=RetryableEngineError("restart casualty"))
    ok = _TracingReplica(1)
    fleet = EngineFleet([dead, ok], routing="round-robin")
    req = fleet.submit_full([5], GREEDY)
    assert req.result == [5, 1]
    # both hops adopted the SAME trace object
    assert dead.seen_trace is ok.seen_trace is req.trace
    spans = [s for s, _ in req.trace.events]
    assert spans == [
        "router_decision replica=0 policy=round-robin reason=round_robin "
        "score=0",
        "failover replica=0 error=RetryableEngineError",
        "router_decision replica=1 policy=round-robin reason=round_robin "
        "score=0",
        "completed",
    ]
    times = [t for _, t in req.trace.events]
    assert times == sorted(times)
    d = req.trace.to_dict()
    assert d["trace_id"] == req.trace.trace_id
    assert len(d["trace_id"]) == 16


def test_router_decision_span_carries_score():
    """Affinity placements stamp the winning rule's strength into the
    span (resident prefix blocks / adapter residency / negative load)."""
    reps = [_TracingReplica(0), _TracingReplica(1)]
    reps[0].prefix_match_len = lambda keys: 3  # replica 0 holds 3 blocks
    for rep in reps:
        rep.block_len = 4
    fleet = EngineFleet(reps, routing="prefix")
    req = fleet.submit_full([1, 2, 3, 4, 5, 6, 7, 8, 9], GREEDY)
    span = [s for s, _ in req.trace.events][0]
    assert span == (
        "router_decision replica=0 policy=prefix reason=prefix_affinity "
        "score=3"
    )


def test_fleet_trace_lands_in_replica_jsonl(generator, tmp_path):
    """End to end on the real engines: the completing replica's trace
    JSONL record carries the propagated trace id AND the router span the
    fleet stamped before the engine ever saw the request."""
    fleet = EngineFleet(
        [
            PagedContinuousBatchingEngine(
                generator, slots=4, buf_len=96, prompt_bucket=16,
                block_len=16, prefill_chunk=32,
                restart_backoff_s=0.01, restart_backoff_max_s=0.02,
                trace_log=str(tmp_path / f"traces_{i}.jsonl"),
            )
            for i in range(2)
        ],
        routing="prefix",
    )
    req = fleet.submit_full(_prompts()[0], GREEDY, timeout=240)
    assert req.result is not None
    spans = [s for s, _ in req.trace.events]
    assert spans[0].startswith("router_decision replica=")
    for expected in ("received", "queued", "admitted", "completed"):
        assert expected in spans, spans
    home = fleet.recent_placements()[0][0]
    deadline = time.monotonic() + 10.0
    records = []
    while not records and time.monotonic() < deadline:
        path = str(tmp_path / f"traces_{home}.jsonl")
        if os.path.exists(path):
            with open(path) as f:
                records = [json.loads(line) for line in f]
        time.sleep(0.01)
    assert len(records) == 1
    assert records[0]["trace_id"] == req.trace.trace_id
    rspans = [e["span"] for e in records[0]["events"]]
    assert rspans[0].startswith("router_decision replica=")
    assert "completed" in rspans


def test_request_trace_ids_are_unique_and_propagate():
    a, b = RequestTrace(), RequestTrace()
    assert a.trace_id != b.trace_id
    pinned = RequestTrace(trace_id="abcd1234abcd1234")
    assert pinned.to_dict()["trace_id"] == "abcd1234abcd1234"


# -------------------------------------------------------- profiler capture


def test_profiler_capture_busy_and_autostop(tmp_path):
    events = []
    cap = ProfilerCapture(
        str(tmp_path), on_event=lambda kind, **f: events.append((kind, f))
    )
    with pytest.raises(ValueError):
        cap.start(0.0)
    trace_dir = cap.start(30.0)
    assert cap.active == trace_dir
    assert os.path.isdir(trace_dir)
    with pytest.raises(CaptureBusyError):
        cap.start(1.0)  # one capture at a time
    assert cap.stop() == trace_dir
    assert cap.active is None
    assert cap.stop() is None  # idempotent
    # a second capture gets a FRESH subdirectory
    second = cap.start(0.05)
    assert second != trace_dir
    # generous: under full-suite load stop_trace serializes TraceMe events
    # from every still-ticking engine fixture — on a starved single-core
    # runner ONE stop_trace has been observed to take ~60s, so the budget
    # must cover a full serialization, not just scheduler jitter
    deadline = time.monotonic() + 120.0
    while cap.active is not None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert cap.active is None  # the timer auto-stopped it
    kinds = [k for k, _ in events]
    assert kinds == [
        "profile_start", "profile_stop", "profile_start", "profile_stop",
    ]
    assert events[0][1]["dir"] == trace_dir
    # the capture produced a loadable (non-empty) trace directory
    assert any(files for _, _, files in os.walk(trace_dir))
