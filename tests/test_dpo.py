"""DPO preference-pair path (BASELINE.json config #4 — the capability the
reference gets from TRL's DPOTrainer, first-party here).

Covers: loss formula against a hand computation from raw logits, chunked vs
full logprob parity, policy==reference init => loss == log 2, and a tiny
end-to-end DPOTrainer run (loss drops, reward accuracy rises, artifact
contract holds)."""

import json
import math
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig
from llm_fine_tune_distributed_tpu.data.preference import (
    build_dpo_arrays,
    load_preference_dataset,
    synthesize_preference_rows,
)
from llm_fine_tune_distributed_tpu.data.tokenizer import load_tokenizer
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params
from llm_fine_tune_distributed_tpu.train.dpo import make_dpo_loss_fn
from llm_fine_tune_distributed_tpu.utils.tree import merge_flat, split_by_mask


SEQ = 96
SYS = "You are a helpful expert."  # short prompt: completions fit in SEQ


def _rows(n=12):
    return [
        {
            "prompt": f"question {i}?",
            "chosen": f"the correct answer {i} with words",
            "rejected": f"wrong {i}",
        }
        for i in range(n)
    ]


@pytest.fixture(scope="module")
def setup():
    tok = load_tokenizer("byte-chatml")
    config = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    arrays = build_dpo_arrays(_rows(4), tok, SEQ, system_prompt=SYS)
    batch = {k: jnp.asarray(v) for k, v in arrays.items()}
    return tok, config, params, batch


def _split(params, config):
    from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask

    cfg = TrainConfig(model_preset="tiny", max_seq_length=SEQ)
    mask = trainable_mask(params, config, cfg)
    return split_by_mask(params, mask)


def _manual_dpo_loss(params, config, batch, beta, train_config):
    """Hand computation straight from full logits (no chunking, no helpers)."""
    def seq_logprob(ids, attn, mask):
        logits, _ = forward(
            params, ids, config,
            padding_mask=attn,
            compute_dtype=jnp.bfloat16,
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tgt = jnp.take_along_axis(logp[:, :-1], ids[:, 1:, None], axis=-1)[..., 0]
        return (tgt * mask[:, 1:]).sum(-1)

    pi_c = seq_logprob(batch["chosen_input_ids"], batch["chosen_attention_mask"], batch["chosen_loss_mask"])
    pi_r = seq_logprob(batch["rejected_input_ids"], batch["rejected_attention_mask"], batch["rejected_loss_mask"])
    # policy == reference here (same params), so ref terms cancel:
    margin = (pi_c - pi_r) - (pi_c - pi_r)
    del margin
    return pi_c, pi_r


def test_dpo_loss_at_init_is_log2(setup):
    """With reference == policy the margin is 0 => loss = -log sigmoid(0) = log 2."""
    _, config, params, batch = setup
    trainable, frozen = _split(params, config)
    cfg = TrainConfig(model_preset="tiny", max_seq_length=SEQ, attention_impl="xla",
                      gradient_checkpointing=False)
    loss_fn = make_dpo_loss_fn(config, cfg)
    ref = {k: v.astype(jnp.bfloat16) for k, v in trainable.items()}
    frozen_bf16 = {k: v.astype(jnp.bfloat16) for k, v in frozen.items()}
    loss, aux = jax.jit(loss_fn)(
        {k: v for k, v in trainable.items()}, ref, frozen_bf16, batch
    )
    assert abs(float(loss) - math.log(2.0)) < 2e-2, float(loss)
    assert abs(float(aux["rewards_margin"])) < 1e-2


@pytest.mark.slow
def test_dpo_chunked_matches_full(setup):
    """loss_chunk_size path must agree with the single-unembed path."""
    _, config, params, batch = setup
    trainable, frozen = _split(params, config)
    frozen = {k: v.astype(jnp.bfloat16) for k, v in frozen.items()}
    # perturb the policy so the margin is nonzero (loss != log 2)
    pol = {k: v + 0.01 * (hash(k) % 7 - 3) for k, v in trainable.items()}
    ref = {k: v.astype(jnp.bfloat16) for k, v in trainable.items()}

    losses = {}
    for chunk in (None, 32):
        cfg = TrainConfig(model_preset="tiny", max_seq_length=SEQ, attention_impl="xla",
                          gradient_checkpointing=False, loss_chunk_size=chunk)
        loss, aux = jax.jit(make_dpo_loss_fn(config, cfg))(pol, ref, frozen, batch)
        losses[chunk] = (float(loss), float(aux["rewards_margin"]))
    assert losses[None][0] == pytest.approx(losses[32][0], abs=2e-3)
    assert losses[None][1] == pytest.approx(losses[32][1], abs=2e-2)


@pytest.mark.slow
def test_dpo_loss_matches_manual_logits(setup):
    """Framework sequence logprobs must match a from-scratch log_softmax gather."""
    _, config, params, batch = setup
    trainable, frozen = _split(params, config)
    frozen_b = {k: v.astype(jnp.bfloat16) for k, v in frozen.items()}
    cfg = TrainConfig(model_preset="tiny", max_seq_length=SEQ, attention_impl="xla",
                      gradient_checkpointing=False, dpo_beta=0.25)
    ref = {k: v.astype(jnp.bfloat16) for k, v in trainable.items()}
    pol = {k: v + 0.02 for k, v in trainable.items()}
    loss, aux = jax.jit(make_dpo_loss_fn(config, cfg))(pol, ref, frozen_b, batch)

    pi_c, pi_r = _manual_dpo_loss(merge_flat(pol, frozen), config, batch, 0.25, cfg)
    rf_c, rf_r = _manual_dpo_loss(
        merge_flat({k: v.astype(jnp.float32) for k, v in ref.items()}, frozen),
        config, batch, 0.25, cfg,
    )
    margin = (pi_c - pi_r) - (rf_c - rf_r)
    expected = float((-jax.nn.log_sigmoid(0.25 * margin)).mean())
    assert float(loss) == pytest.approx(expected, rel=0.05, abs=5e-3)


def test_preference_synthesis_and_loading(tmp_path):
    qa = [{"full-question": f"q{i}", "answer": f"a{i}"} for i in range(10)]
    rows = synthesize_preference_rows(qa, seed=3)
    assert len(rows) == 10
    assert all(r["chosen"] != r["rejected"] for r in rows)
    # jsonl round-trip with prompt/chosen/rejected schema
    p = tmp_path / "prefs.jsonl"
    with open(p, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    loaded = load_preference_dataset(str(p))
    assert loaded == rows


@pytest.mark.slow
def test_dpo_end_to_end(tmp_path):
    """Tiny DPOTrainer run on the 8-device mesh: loss below log2, accuracy
    above chance, SFT artifact contract preserved."""
    from llm_fine_tune_distributed_tpu.train.dpo import DPOTrainer

    rows = _rows(48)
    p = tmp_path / "prefs.jsonl"
    with open(p, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    out = tmp_path / "outputs"
    config = TrainConfig(
        model_name="tiny-random",
        model_preset="tiny",
        tokenizer_path="byte-chatml",
        data_dir=str(tmp_path),
        dataset_file="prefs.jsonl",
        output_dir=str(out),
        objective="dpo",
        system_prompt=SYS,
        dpo_beta=0.5,
        epochs=3,
        per_device_batch_size=2,
        gradient_accumulation_steps=2,
        learning_rate=2e-3,
        max_seq_length=SEQ,
        eval_steps=5,
        logging_steps=2,
        save_steps=100,
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=1),
    )
    trainer = DPOTrainer(config)
    trainer.train()

    history = trainer.metrics.history
    losses = [h["loss"] for h in history if "loss" in h]
    accs = [h["rewards_accuracy"] for h in history if "rewards_accuracy" in h]
    assert losses[-1] < math.log(2.0), f"DPO loss never fell below log2: {losses}"
    assert losses[-1] < losses[0]
    assert accs[-1] > 0.6, f"reward accuracy stayed at chance: {accs}"
    evals = [h["eval_rewards_accuracy"] for h in history if "eval_rewards_accuracy" in h]
    assert evals, "eval accuracy never logged"

    assert (out / "best_model" / "model.safetensors").exists()
    assert (out / "training_summary.json").exists()


@pytest.mark.slow
def test_dpo_pipeline_end_to_end(tmp_path):
    """DPO x pipe (VERDICT r2 #3): pipe=2 x fsdp=2 mesh runs the DPO
    objective as GPipe schedules (policy + reference), learns past log2,
    and first-step loss agrees with the flat mesh (same init, same data)."""
    from llm_fine_tune_distributed_tpu.train.dpo import DPOTrainer

    rows = _rows(48)
    p = tmp_path / "prefs.jsonl"
    with open(p, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")

    def cfg(out, mesh):
        return TrainConfig(
            model_name="tiny-random",
            model_preset="tiny",
            tokenizer_path="byte-chatml",
            data_dir=str(tmp_path),
            dataset_file="prefs.jsonl",
            output_dir=str(out),
            objective="dpo",
            system_prompt=SYS,
            dpo_beta=0.5,
            epochs=2,
            per_device_batch_size=2,
            gradient_accumulation_steps=2,
            learning_rate=2e-3,
            max_seq_length=SEQ,
            eval_steps=5,
            logging_steps=2,
            save_steps=100,
            mesh=mesh,
        )

    flat = DPOTrainer(cfg(tmp_path / "flat", MeshConfig(data=1, fsdp=2, tensor=1, seq=1)))
    flat.train()
    pipe = DPOTrainer(
        cfg(tmp_path / "pipe", MeshConfig(data=1, fsdp=2, tensor=1, seq=1, pipe=2))
    )
    pipe.train()

    flat_losses = [h["loss"] for h in flat.metrics.history if "loss" in h]
    pipe_losses = [h["loss"] for h in pipe.metrics.history if "loss" in h]
    # both start at ~log2 (identical-policy DPO) and learn below it
    assert pipe_losses[0] == pytest.approx(flat_losses[0], rel=2e-2)
    assert pipe_losses[-1] < math.log(2.0), f"pipe DPO never learned: {pipe_losses}"
    accs = [h["rewards_accuracy"] for h in pipe.metrics.history if "rewards_accuracy" in h]
    assert accs[-1] > 0.6
    evals = [
        h["eval_rewards_accuracy"] for h in pipe.metrics.history
        if "eval_rewards_accuracy" in h
    ]
    assert evals, "pipe DPO eval accuracy never logged"
    assert (tmp_path / "pipe" / "best_model" / "model.safetensors").exists()
