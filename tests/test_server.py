"""HTTP serving (infer/server.py): healthz + /v1/generate against a tiny
model dir — the serving capability the reference only templates
(examples/openshift-deploy.yaml, SURVEY.md C21)."""

import json
import os
import socket
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.hf_io import save_hf_checkpoint
from llm_fine_tune_distributed_tpu.models.transformer import init_params


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    d = tmp_path_factory.mktemp("serve") / "best_model"
    save_hf_checkpoint(params, str(d))
    ByteChatMLTokenizer().save_pretrained(str(d))
    with open(d / "config.json", "w") as f:
        json.dump(
            {
                "model_type": mc.name,
                "vocab_size": mc.vocab_size,
                "hidden_size": mc.hidden_size,
                "intermediate_size": mc.intermediate_size,
                "num_hidden_layers": mc.num_layers,
                "num_attention_heads": mc.num_heads,
                "num_key_value_heads": mc.num_kv_heads,
                "rope_theta": mc.rope_theta,
                "max_position_embeddings": mc.max_position_embeddings,
                "rms_norm_eps": mc.rms_norm_eps,
                "tie_word_embeddings": mc.tie_word_embeddings,
                "no_rope_layers": list(mc.no_rope_layers),
            },
            f,
        )
    return str(d)


def _start_server(model_dir, timeout_s=120, **serve_kwargs):
    """Start serve() on a free port in a daemon thread; wait for /healthz."""
    from llm_fine_tune_distributed_tpu.infer.server import serve

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    t = threading.Thread(
        target=serve, args=(model_dir, "127.0.0.1", port),
        kwargs=serve_kwargs, daemon=True,
    )
    t.start()
    base = f"http://127.0.0.1:{port}"
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(f"{base}/healthz", timeout=2) as r:
                if r.status == 200:
                    return base
        except OSError:
            time.sleep(0.25)
    raise RuntimeError("server did not become healthy")


@pytest.fixture(scope="module")
def server(model_dir):
    return _start_server(model_dir)


def test_healthz(server):
    with urllib.request.urlopen(f"{server}/healthz") as r:
        assert r.read() == b"ok"


def test_generate(server):
    req = urllib.request.Request(
        f"{server}/v1/generate",
        data=json.dumps(
            {"question": "How many cups in a gallon?", "max_new_tokens": 8, "greedy": True}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        payload = json.loads(r.read())
    assert isinstance(payload["answer"], str)


def test_bad_request(server):
    req = urllib.request.Request(
        f"{server}/v1/generate", data=b'{"nope": 1}',
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 400


def test_unknown_path_404(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(f"{server}/nope", timeout=10)
    assert e.value.code == 404


def test_profile_disabled_404(server):
    """Without --profile-dir the endpoint doesn't exist."""
    req = urllib.request.Request(f"{server}/v1/profile", data=b"{}")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 404


@pytest.mark.slow
def test_profile_capture_endpoint(model_dir, tmp_path):
    """POST /v1/profile starts a bounded jax.profiler capture: one at a
    time (409 while running), 400 on a bad duration, auto-stop frees the
    next capture into a FRESH subdirectory, and stopped captures leave a
    non-empty trace dir (the artifact tensorboard loads). slow: a second
    server startup plus real wall-clock captures; the ProfilerCapture
    unit tests cover the same semantics in tier-1."""
    base = _start_server(model_dir, profile_dir=str(tmp_path / "profiles"))

    def post(body):
        req = urllib.request.Request(
            f"{base}/v1/profile", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    first = post({"duration_s": 2.0})
    assert first["profiling"] is True
    trace_dir = first["trace_dir"]
    assert os.path.isdir(trace_dir)
    with pytest.raises(urllib.error.HTTPError) as e:
        post({"duration_s": 1.0})  # one capture at a time
    assert e.value.code == 409
    with pytest.raises(urllib.error.HTTPError) as e:
        post({"duration_s": -3})
    assert e.value.code == 400
    # the timer auto-stops the first capture; the next start then succeeds
    second = None
    deadline = time.time() + 60
    while second is None and time.time() < deadline:
        try:
            second = post({"duration_s": 0.2})
        except urllib.error.HTTPError as err:
            assert err.code == 409
            time.sleep(0.25)
    assert second is not None and second["trace_dir"] != trace_dir

    def has_files(d):
        return any(files for _, _, files in os.walk(d))

    deadline = time.time() + 60
    while time.time() < deadline and not (
        has_files(trace_dir) and has_files(second["trace_dir"])
    ):
        time.sleep(0.25)
    assert has_files(trace_dir) and has_files(second["trace_dir"])


@pytest.mark.slow
def test_concurrent_generate_batched(server):
    """Several simultaneous identical-config requests all succeed and agree
    (greedy + shared seed -> the batcher groups them; batched greedy rows
    are bit-identical to solo decode)."""
    def ask(q):
        req = urllib.request.Request(
            f"{server}/v1/generate",
            data=json.dumps(
                {"question": q, "max_new_tokens": 6, "greedy": True}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=180) as r:
            return json.loads(r.read())["answer"]

    questions = [f"question {i}?" for i in range(4)]
    answers = [None] * 4
    threads = [
        threading.Thread(target=lambda i=i: answers.__setitem__(i, ask(questions[i])))
        for i in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=200)
    assert all(isinstance(a, str) for a in answers), answers
    # same question solo must give the same greedy answer
    assert ask(questions[0]) == answers[0]


def test_serve_int8(model_dir):
    """--quantize int8 serving path answers requests."""
    base = _start_server(model_dir, quantize="int8")
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"question": "q?", "max_new_tokens": 4, "greedy": True}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        assert isinstance(json.loads(r.read())["answer"], str)


@pytest.mark.slow
def test_speculative_request_field(server):
    """POST /v1/generate accepts "speculative": K for greedy AND sampled
    requests (sampled verification is rejection sampling, infer/generate.py)."""
    def post(body):
        req = urllib.request.Request(
            f"{server}/v1/generate", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        return urllib.request.urlopen(req, timeout=120)

    with post(
        {"question": "water?", "max_new_tokens": 4, "greedy": True, "speculative": 4}
    ) as r:
        body = json.loads(r.read())
        assert isinstance(body["answer"], str)
        # acceptance-rate telemetry rides the response so clients can see
        # whether the speculation they asked for pays off
        assert 0.0 <= body["speculative"]["acceptance_rate"] <= 1.0
        assert body["speculative"]["sequential_forwards"] >= 1
    with post({"question": "water?", "max_new_tokens": 4, "speculative": 4}) as r:
        body = json.loads(r.read())
        assert isinstance(body["answer"], str)
        assert "speculative" in body
    # non-speculative requests carry no speculative block
    with post({"question": "water?", "max_new_tokens": 4, "greedy": True}) as r:
        assert "speculative" not in json.loads(r.read())


def test_stream_sse(server):
    """POST /v1/stream: SSE events with text deltas whose concatenation
    equals the non-streamed answer for the same greedy request."""
    body = {"question": "How many cups in a gallon?", "max_new_tokens": 8, "greedy": True}
    req = urllib.request.Request(
        f"{server}/v1/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        answer = json.loads(r.read())["answer"]

    sreq = urllib.request.Request(
        f"{server}/v1/stream", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(sreq, timeout=120) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        raw = r.read().decode()
    events = [
        json.loads(line[len("data: "):])
        for line in raw.splitlines()
        if line.startswith("data: ")
    ]
    assert events and events[-1].get("done") is True
    text = "".join(e.get("delta", "") for e in events)
    # decode_reply strips; the streamed deltas carry the raw decode
    assert text.strip() == answer
    assert events[-1]["n_tokens"] >= 1


def test_stream_bad_request(server):
    req = urllib.request.Request(
        f"{server}/v1/stream", data=b"{}",
        headers={"Content-Type": "application/json"},
    )
    try:
        urllib.request.urlopen(req, timeout=30)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_stream_speculative_400_names_alternatives(server):
    """"speculative" on /v1/stream is a 400 when the server was started
    WITHOUT --speculative (the engine has no fused verify step compiled)
    and the error names the supported routes."""
    body = {"question": "q?", "max_new_tokens": 4, "greedy": True, "speculative": 4}
    req = urllib.request.Request(
        f"{server}/v1/stream", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        urllib.request.urlopen(req, timeout=30)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400
        msg = json.loads(e.read())["error"]
        assert "POST /v1/generate" in msg and "/v1/stream" in msg


def test_stats_endpoint(server):
    """GET /v1/stats: live engine counters after serving one request."""
    req = urllib.request.Request(
        f"{server}/v1/generate",
        data=json.dumps({"question": "q?", "max_new_tokens": 4, "greedy": True}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        r.read()
    with urllib.request.urlopen(f"{server}/v1/stats", timeout=30) as r:
        stats = json.loads(r.read())
    assert stats["engine"] == "continuous"
    assert stats["tokens_served"] >= 1
    assert stats["requests_completed"] >= 1
    assert stats["queue_depth"] == 0
    assert 0.0 <= stats["slot_occupancy"] <= 1.0


def test_stats_endpoint_window_engine(model_dir):
    """--engine window still serves /v1/stats (reduced: queue depth only)."""
    base = _start_server(model_dir, engine_kind="window")
    with urllib.request.urlopen(f"{base}/v1/stats", timeout=30) as r:
        stats = json.loads(r.read())
    assert stats["engine"] == "window"
    assert "queue_depth" in stats
    with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
        text = r.read().decode()
    assert 'serving_info{engine="window"} 1' in text  # reduced, still valid


def test_stats_histograms_and_memory(server):
    """/v1/stats carries latency-percentile summaries and the HBM report."""
    with urllib.request.urlopen(f"{server}/v1/stats", timeout=30) as r:
        stats = json.loads(r.read())
    hists = stats["histograms"]
    for name in ("ttft_s", "inter_token_s", "queue_wait_s", "decode_tick_s"):
        assert {"count", "mean", "p50", "p90", "p99"} <= set(hists[name])
    assert stats["uptime_s"] > 0.0
    assert stats["tokens_per_s_1m"] >= 0.0
    assert isinstance(stats["device_memory"], dict)  # {} on CPU
    # residency breakdown (engine.memory_breakdown) — platform-independent
    report = stats["device_memory_report"]
    assert set(report) == {
        "weight_bytes", "kv_pool_bytes", "kv_scale_bytes",
        "bytes_saved_vs_bf16",
    }
    assert report["weight_bytes"] > 0
    assert report["bytes_saved_vs_bf16"] == 0  # unquantized server


def test_metrics_endpoint_prometheus(server):
    """GET /metrics: Prometheus text exposition with the latency histograms
    after at least one request has been served."""
    req = urllib.request.Request(
        f"{server}/v1/generate",
        data=json.dumps({"question": "q?", "max_new_tokens": 4, "greedy": True}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        r.read()
    with urllib.request.urlopen(f"{server}/metrics", timeout=30) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        assert "version=0.0.4" in r.headers["Content-Type"]
        text = r.read().decode()
    assert "# TYPE serving_tokens_served_total counter" in text
    assert "# TYPE serving_ttft_seconds histogram" in text
    assert "# TYPE serving_inter_token_seconds histogram" in text
    count_lines = [
        line for line in text.splitlines()
        if line.startswith("serving_ttft_seconds_count")
    ]
    assert count_lines and int(count_lines[0].split()[-1]) >= 1


def test_generate_with_trace(server):
    """'trace': true -> the response carries the request's lifecycle span
    timeline (received -> ... -> completed, nondecreasing offsets)."""
    req = urllib.request.Request(
        f"{server}/v1/generate",
        data=json.dumps({
            "question": "q?", "max_new_tokens": 4, "greedy": True,
            "trace": True,
        }).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        payload = json.loads(r.read())
    trace = payload["trace"]
    spans = [e["span"] for e in trace["events"]]
    for expected in ("received", "queued", "admitted", "first_token", "completed"):
        assert expected in spans, spans
    offsets = [e["t_s"] for e in trace["events"]]
    assert offsets == sorted(offsets)
    assert trace["total_s"] >= 0.0
    # without the flag the response stays lean
    lean = urllib.request.Request(
        f"{server}/v1/generate",
        data=json.dumps({"question": "q?", "max_new_tokens": 4, "greedy": True}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(lean, timeout=120) as r:
        assert "trace" not in json.loads(r.read())


def test_slo_endpoint(server):
    """GET /v1/slo: the burn-rate report over the four pinned objectives,
    each with a fast and slow window."""
    with urllib.request.urlopen(f"{server}/v1/slo", timeout=30) as r:
        report = json.loads(r.read())
    assert report["engine"] == "continuous"
    assert report["compliant"] in (True, False)
    assert set(report["objectives"]) == {
        "ttft_p99", "inter_token_p99", "error_rate", "availability",
    }
    for obj in report["objectives"].values():
        assert set(obj["windows"]) == {"fast", "slow"}
        for w in obj["windows"].values():
            assert w["burn_rate"] >= 0.0
            assert 0.0 <= w["bad_fraction"] <= 1.0


def test_history_endpoint_series_and_errors(server):
    """GET /v1/history?metric=&window=: counter series carry per-sample
    deltas, gauges don't; bad queries are 400s naming the problem."""
    url = f"{server}/v1/history?metric=queue_depth&window=60"
    with urllib.request.urlopen(url, timeout=30) as r:
        series = json.loads(r.read())
    assert series["metric"] == "queue_depth"
    assert series["kind"] == "gauge"
    assert series["window_s"] == 60.0
    assert isinstance(series["samples"], list)
    url = f"{server}/v1/history?metric=tokens_served"
    with urllib.request.urlopen(url, timeout=30) as r:
        series = json.loads(r.read())
    assert series["kind"] == "counter"
    assert series["window_s"] is None
    for point in series["samples"]:
        assert {"age_s", "value", "delta"} <= set(point)
    for bad in (
        "/v1/history",  # missing ?metric
        "/v1/history?metric=not_a_metric",
        "/v1/history?metric=queue_depth&window=-5",
        "/v1/history?metric=queue_depth&window=abc",
    ):
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{server}{bad}", timeout=30)
        assert e.value.code == 400


def test_flight_endpoint(server):
    """GET /v1/flight: the live flight-recorder ring — admissions from
    served requests appear, ?limit= truncates, limit<=0 is a 400."""
    req = urllib.request.Request(
        f"{server}/v1/generate",
        data=json.dumps(
            {"question": "q?", "max_new_tokens": 4, "greedy": True}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        r.read()
    with urllib.request.urlopen(f"{server}/v1/flight", timeout=30) as r:
        events = json.loads(r.read())["events"]
    assert events and all("kind" in e and "t_s" in e for e in events)
    with urllib.request.urlopen(f"{server}/v1/flight?limit=2", timeout=30) as r:
        assert len(json.loads(r.read())["events"]) <= 2
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(f"{server}/v1/flight?limit=0", timeout=30)
    assert e.value.code == 400


def test_slo_history_flight_404_on_window_engine(model_dir):
    """The window engine has no metric ring / flight recorder; the SLO
    surfaces answer 404, not 500."""
    base = _start_server(model_dir, engine_kind="window")
    for path in ("/v1/slo", "/v1/history?metric=queue_depth", "/v1/flight"):
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{base}{path}", timeout=30)
        assert e.value.code == 404


# ------------------------------------------------- engine-level speculation


def test_speculative_flag_validation_at_startup():
    """Bad speculation flag combinations fail AT STARTUP with a clear
    message (parity with infer/cli.py), before the model even loads — so
    the model_dir can be bogus here and the check still runs."""
    from llm_fine_tune_distributed_tpu.infer.server import serve

    with pytest.raises(ValueError, match="--draft-dir requires --speculative"):
        serve("/nonexistent", draft_dir="/also/nonexistent")
    with pytest.raises(ValueError, match="window engine"):
        serve("/nonexistent", speculative_k=4, engine_kind="window")


@pytest.fixture(scope="module")
def spec_server(model_dir):
    """A continuous engine started with --speculative 4: speculative
    requests (streaming included) ride the fused slot batch."""
    return _start_server(model_dir, speculative_k=4, slots=4)


def test_speculative_server_generate_reports_draft_counts(spec_server):
    """On a --speculative server, /v1/generate speculation rides the slot
    engine and the response carries the request's OWN draft counts."""
    body = {
        "question": "water water water water?", "max_new_tokens": 12,
        "greedy": True, "speculative": 4,
    }
    req = urllib.request.Request(
        f"{spec_server}/v1/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        payload = json.loads(r.read())
    assert isinstance(payload["answer"], str)
    spec = payload["speculative"]
    assert 0.0 <= spec["acceptance_rate"] <= 1.0
    assert spec["draft_tokens_proposed"] >= spec["draft_tokens_accepted"] >= 0
    # slot engines have no whole-batch sequential-forward count
    assert "sequential_forwards" not in spec


def test_speculative_server_stream_accepts_k(spec_server):
    """/v1/stream accepts 'speculative': K on a --speculative engine, and
    the streamed deltas concatenate to the non-streamed greedy answer."""
    body = {
        "question": "water water water water?", "max_new_tokens": 12,
        "greedy": True, "speculative": 4,
    }
    req = urllib.request.Request(
        f"{spec_server}/v1/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        answer = json.loads(r.read())["answer"]
    sreq = urllib.request.Request(
        f"{spec_server}/v1/stream", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(sreq, timeout=120) as r:
        assert r.status == 200
        raw = r.read().decode()
    events = [
        json.loads(line[len("data: "):])
        for line in raw.splitlines()
        if line.startswith("data: ")
    ]
    assert events and events[-1].get("done") is True
    text = "".join(e.get("delta", "") for e in events)
    assert text.strip() == answer


def test_speculative_server_stats_counters(spec_server):
    """GET /v1/stats surfaces the draft counters + derived acceptance rate
    after speculative traffic has been served."""
    with urllib.request.urlopen(f"{spec_server}/v1/stats", timeout=30) as r:
        stats = json.loads(r.read())
    assert stats["draft_tokens_proposed"] >= 1
    assert 0 <= stats["draft_tokens_accepted"] <= stats["draft_tokens_proposed"]
    assert 0.0 <= stats["draft_acceptance_rate"] <= 1.0
    assert stats["mean_tokens_per_step"] > 0.0


# ------------------------------------------------- self-healing + drain


def _start_controlled(model_dir, **serve_kwargs):
    """_start_server variant returning (base, serve_thread, control): the
    control dict carries the drain entry points, since a signal handler
    can only be installed on the main thread (not a test worker)."""
    from llm_fine_tune_distributed_tpu.infer.server import serve

    control = {}
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    t = threading.Thread(
        target=serve, args=(model_dir, "127.0.0.1", port),
        kwargs={"control": control, **serve_kwargs}, daemon=True,
    )
    t.start()
    base = f"http://127.0.0.1:{port}"
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(f"{base}/healthz", timeout=2) as r:
                if r.status == 200:
                    return base, t, control
        except OSError:
            time.sleep(0.25)
    raise RuntimeError("server did not become healthy")


def _post(base, path, body, timeout=120):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    return urllib.request.urlopen(req, timeout=timeout)


def test_drain_finishes_in_flight_and_exits(model_dir):
    """The SIGTERM path: drain flips /healthz to 503 draining, sheds new
    admissions with 503 + Retry-After, lets the in-flight request finish,
    and returns from serve() (process exit 0) within the drain timeout."""
    base, serve_thread, control = _start_controlled(
        model_dir, drain_timeout_s=60.0
    )
    answers = []
    inflight = threading.Thread(
        target=lambda: answers.append(json.loads(_post(
            base, "/v1/generate",
            {"question": "q?", "max_new_tokens": 48, "greedy": True},
        ).read())["answer"])
    )
    inflight.start()
    time.sleep(0.3)  # let it admit
    control["begin_drain"]()  # what the SIGTERM handler calls

    with pytest.raises(urllib.error.HTTPError) as he:
        urllib.request.urlopen(f"{base}/healthz", timeout=10)
    assert he.value.code == 503
    assert json.loads(he.value.read())["status"] == "draining"
    assert int(he.value.headers["Retry-After"]) >= 1

    with pytest.raises(urllib.error.HTTPError) as pe:
        _post(base, "/v1/generate",
              {"question": "late?", "max_new_tokens": 4, "greedy": True},
              timeout=30)
    assert pe.value.code == 503
    assert json.loads(pe.value.read())["error"]["kind"] == "draining"
    assert int(pe.value.headers["Retry-After"]) >= 1

    inflight.join(timeout=180)
    assert answers and isinstance(answers[0], str)  # in-flight unharmed
    serve_thread.join(timeout=120)
    assert not serve_thread.is_alive()  # serve() returned -> clean exit 0


def test_queue_overflow_maps_to_429(model_dir):
    """Admission-queue overflow surfaces as HTTP 429 with a finite integer
    Retry-After header and a structured queue_overflow body."""
    base, _, control = _start_controlled(
        model_dir, slots=1, max_queue_depth=1
    )
    body = {"question": "q?", "max_new_tokens": 256, "greedy": True}
    holders = [
        threading.Thread(target=lambda: _post(base, "/v1/generate", body).read())
        for _ in range(2)
    ]
    holders[0].start()  # occupies the single slot
    # wait until it is actually admitted before queueing the second
    engine = control["cont_engine"]
    deadline = time.time() + 60
    while time.time() < deadline:
        if engine.stats_snapshot()["live_slots"] >= 1:
            break
        time.sleep(0.02)
    holders[1].start()  # fills the depth-1 queue
    while time.time() < deadline:
        if engine.stats_snapshot()["queue_depth"] >= 1:
            break
        time.sleep(0.02)
    with pytest.raises(urllib.error.HTTPError) as he:
        _post(base, "/v1/generate",
              {"question": "third?", "max_new_tokens": 4, "greedy": True},
              timeout=30)
    assert he.value.code == 429
    err = json.loads(he.value.read())["error"]
    assert err["kind"] == "queue_overflow"
    assert err["retryable"] is True
    assert int(he.value.headers["Retry-After"]) >= 1
    for t in holders:
        t.join(timeout=180)


def test_stream_emits_error_event_then_engine_recovers(model_dir):
    """A decode failure mid-stream ends the SSE body with a terminal
    ``event: error`` chunk (structured, not silent truncation) — and the
    supervised engine serves the next request normally."""
    base, _, control = _start_controlled(
        model_dir, restart_backoff_s=0.01, restart_backoff_max_s=0.02
    )
    # warm the jit caches so the fault lands in steady-state decode
    _post(base, "/v1/generate",
          {"question": "warm?", "max_new_tokens": 4, "greedy": True}).read()
    control["cont_engine"].faults.fail_decode_next(1)
    with _post(base, "/v1/stream",
               {"question": "q?", "max_new_tokens": 16, "greedy": True}) as r:
        assert r.status == 200  # headers were already committed
        raw = r.read().decode()
    assert "event: error" in raw
    lines = raw.splitlines()
    err = json.loads(lines[lines.index("event: error") + 1][len("data: "):])
    assert err["kind"] == "engine_restarting"
    assert err["retryable"] is True
    # recovered in-process: the next request decodes fine
    answer = json.loads(_post(
        base, "/v1/generate",
        {"question": "after?", "max_new_tokens": 4, "greedy": True},
    ).read())["answer"]
    assert isinstance(answer, str)
    assert control["cont_engine"].stats_snapshot()["engine_restarts"] >= 1


def test_healthz_unhealthy_once_circuit_opens(model_dir):
    """circuit_threshold=1: the first decode failure opens the breaker, the
    engine goes terminally unhealthy, and /healthz reports 503 with the
    structured terminal error — the orchestrator's recycle signal."""
    base, _, control = _start_controlled(
        model_dir, circuit_threshold=1, restart_backoff_s=0.01
    )
    _post(base, "/v1/generate",
          {"question": "warm?", "max_new_tokens": 4, "greedy": True}).read()
    control["cont_engine"].faults.fail_decode_next(1)
    with pytest.raises(urllib.error.HTTPError) as pe:
        _post(base, "/v1/generate",
              {"question": "q?", "max_new_tokens": 16, "greedy": True},
              timeout=60)
    assert pe.value.code == 503
    assert json.loads(pe.value.read())["error"]["kind"] == "circuit_open"
    deadline = time.time() + 30
    while time.time() < deadline and control["cont_engine"].healthy:
        time.sleep(0.02)
    with pytest.raises(urllib.error.HTTPError) as he:
        urllib.request.urlopen(f"{base}/healthz", timeout=10)
    assert he.value.code == 503
    body = json.loads(he.value.read())
    assert body["status"] == "unhealthy"
    assert body["circuit_state"] == "open"
    assert body["error"]["kind"] == "circuit_open"


# ------------------------------------------------- multi-tenant LoRA serving


def test_adapter_flag_validation_at_startup():
    """Bad adapter flag combinations fail AT STARTUP, before the model
    loads (parity with the --speculative checks above), naming what IS
    supported."""
    from llm_fine_tune_distributed_tpu.infer.server import serve

    with pytest.raises(ValueError, match="continuous|paged"):
        serve("/nonexistent", adapter_dir="/whatever", engine_kind="window")
    with pytest.raises(ValueError, match="--adapter-dir not found"):
        serve("/nonexistent", adapter_dir="/no/such/dir")


@pytest.fixture(scope="module")
def adapter_root(tmp_path_factory):
    """Two PEFT adapters built against the same tiny base the model_dir
    checkpoint holds (init_params PRNGKey 0), with non-zero B."""
    from llm_fine_tune_distributed_tpu.config import TrainConfig
    from llm_fine_tune_distributed_tpu.parallel.lora import (
        add_lora_params,
        save_lora_adapter,
    )

    mc = get_preset("tiny")
    base = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    root = tmp_path_factory.mktemp("srv_adapters")
    for name, seed in (("acme", 1), ("globex", 2)):
        params = add_lora_params(
            base, jax.random.PRNGKey(seed), rank=4, alpha=8.0
        )

        # large-magnitude B so the adapted greedy path visibly diverges
        # from base (tiny random weights need a big shove to flip argmax)
        def bump(node, scale=0.5 * seed):
            if isinstance(node, dict):
                if "lora_b" in node:
                    node = dict(node)
                    node["lora_b"] = jnp.ones_like(node["lora_b"]) * scale
                    return node
                return {k: bump(v) for k, v in node.items()}
            return node

        save_lora_adapter(
            bump(params), str(root / name),
            TrainConfig(freeze_strategy="lora", lora_rank=4, lora_alpha=8.0),
        )
    return str(root)


@pytest.fixture(scope="module")
def adapter_server(model_dir, adapter_root):
    return _start_server(
        model_dir, adapter_dir=adapter_root, slots=4, max_adapters=4
    )


def test_generate_with_adapter(adapter_server):
    """The 'adapter' request field selects the tenant's LoRA delta: the
    adapted greedy answer differs from the base answer for the same
    request, and the base answer is unchanged by adapter traffic."""
    body = {"question": "What is 2+2?", "max_new_tokens": 8, "greedy": True}
    with _post(adapter_server, "/v1/generate", body) as r:
        base_answer = json.loads(r.read())["answer"]
    with _post(
        adapter_server, "/v1/generate", {**body, "adapter": "acme"}
    ) as r:
        acme_answer = json.loads(r.read())["answer"]
    assert acme_answer != base_answer
    with _post(adapter_server, "/v1/generate", body) as r:
        assert json.loads(r.read())["answer"] == base_answer


def test_generate_unknown_adapter_404_lists_known(adapter_server):
    with pytest.raises(urllib.error.HTTPError) as he:
        _post(
            adapter_server, "/v1/generate",
            {"question": "q?", "max_new_tokens": 4, "adapter": "ghost"},
            timeout=30,
        )
    assert he.value.code == 404
    err = json.loads(he.value.read())["error"]
    assert err["kind"] == "unknown_adapter"
    assert set(err["known_adapters"]) == {"acme", "globex"}


def test_adapter_without_registry_404(server):
    """The plain server (no --adapter-dir) rejects adapter requests with
    a structured error telling the operator which flag is missing."""
    with pytest.raises(urllib.error.HTTPError) as he:
        _post(
            server, "/v1/generate",
            {"question": "q?", "max_new_tokens": 4, "adapter": "acme"},
            timeout=30,
        )
    assert he.value.code == 404
    err = json.loads(he.value.read())["error"]
    assert err["kind"] == "unknown_adapter"
    assert "--adapter-dir" in err["message"]


def test_stream_with_adapter(adapter_server):
    """SSE streaming rides the shared batch WITH the tenant's delta: the
    streamed deltas concatenate to the non-streamed adapted answer."""
    body = {
        "question": "How many cups in a gallon?", "max_new_tokens": 8,
        "greedy": True, "adapter": "acme",
    }
    with _post(adapter_server, "/v1/generate", body) as r:
        answer = json.loads(r.read())["answer"]
    with _post(adapter_server, "/v1/stream", body) as r:
        raw = r.read().decode()
    events = [
        json.loads(line[len("data: "):])
        for line in raw.splitlines()
        if line.startswith("data: ")
    ]
    assert events and events[-1].get("done") is True
    assert "".join(e.get("delta", "") for e in events).strip() == answer


def test_adapter_stats_and_metrics_per_tenant(adapter_server):
    """/v1/stats carries the per-tenant map and pool gauges; /metrics
    carries the tenant-labelled series."""
    with urllib.request.urlopen(f"{adapter_server}/v1/stats", timeout=30) as r:
        stats = json.loads(r.read())
    assert stats["per_tenant"]["acme"]["requests"] >= 1
    assert stats["per_tenant"]["acme"]["tokens"] >= 1
    assert stats["adapters_resident"] >= 1
    assert stats["adapter_loads"] >= 1
    with urllib.request.urlopen(f"{adapter_server}/metrics", timeout=30) as r:
        text = r.read().decode()
    assert 'serving_tenant_tokens_total{tenant="acme"}' in text
    assert "serving_adapters_resident" in text


def test_adapter_field_window_engine_400(model_dir):
    """A window-engine server rejects 'adapter' with a 400 naming the
    supported alternatives (validation parity with 'speculative')."""
    base = _start_server(model_dir, engine_kind="window")
    with pytest.raises(urllib.error.HTTPError) as he:
        _post(
            base, "/v1/generate",
            {"question": "q?", "max_new_tokens": 4, "adapter": "acme"},
            timeout=30,
        )
    assert he.value.code == 400
    msg = json.loads(he.value.read())["error"]
    assert "--adapter-dir" in msg and "continuous" in msg
