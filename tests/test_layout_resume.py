"""Cross-layout checkpoint resume (train/layout.py): a run checkpointed on a
flat mesh resumes on a pipe mesh and vice versa, with params AND optimizer
moments transformed exactly (elastic resize — beyond the reference's
restart-from-scratch semantics, SURVEY.md §5.4)."""

import json
import os

import numpy as np
import pytest

import jax

from llm_fine_tune_distributed_tpu.config import MeshConfig
from llm_fine_tune_distributed_tpu.parallel.pipeline import (
    STACKED_PREFIX,
    unstack_flat_layer_leaves,
)

from tests.test_train_e2e import make_config, qa_parquet  # noqa: F401 (fixture)


def _flat_params(state):
    """Current state's merged params in flat per-layer keying (host numpy)."""
    merged = {**state.trainable, **state.frozen}
    if any(k.startswith(STACKED_PREFIX) for k in merged):
        merged = unstack_flat_layer_leaves(
            {k: np.asarray(v) for k, v in merged.items()}
        )
    return {k: np.asarray(v) for k, v in merged.items()}


def _run(cfg):
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    trainer = SFTTrainer(cfg)
    trainer.train()
    return trainer


@pytest.mark.slow
@pytest.mark.parametrize("first_pipe,second_pipe", [(1, 2), (2, 1)])
def test_cross_layout_resume(qa_parquet, tmp_path, first_pipe, second_pipe):  # noqa: F811
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data_dir, dataset_file = qa_parquet
    out = tmp_path / f"xresume_{first_pipe}_{second_pipe}"

    def mesh(pipe):
        return MeshConfig(data=1, fsdp=2, tensor=1, seq=1, pipe=pipe)

    cfg1 = make_config(
        out, data_dir, dataset_file,
        epochs=1, save_steps=5, eval_steps=100, mesh=mesh(first_pipe),
    )
    t1 = _run(cfg1)
    params_before = _flat_params(t1.state)
    steps_done = int(jax.device_get(t1.state.step))
    assert steps_done > 0

    # resume the SAME output dir under the other layout
    cfg2 = make_config(
        out, data_dir, dataset_file,
        epochs=2, save_steps=100, eval_steps=100, mesh=mesh(second_pipe),
        resume_from_checkpoint="latest",
    )
    t2 = SFTTrainer(cfg2)
    # _prepare_* ran in __init__; drive the resume path via train()
    # but first verify the transformed state BEFORE further steps by
    # resuming manually:
    from llm_fine_tune_distributed_tpu.train.checkpoints import CheckpointManager

    ckpt = CheckpointManager(os.path.join(str(out), "checkpoints"))
    resumed_step = t2._resume(ckpt)
    # the checkpoint rotation keeps the last saves; the resumed step is one
    # of them (<= steps at end of run 1)
    assert 0 < resumed_step <= steps_done
    params_after = _flat_params(t2.state)
    assert set(params_after) == set(params_before)
    if resumed_step == steps_done:
        for k in params_before:
            np.testing.assert_array_equal(
                params_before[k], params_after[k], err_msg=k
            )

    # and training continues from there without blowing up
    summary = t2.train()
    assert np.isfinite(summary["final_train_loss"])
