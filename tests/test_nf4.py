"""NF4 quantization + QLoRA path (BASELINE.json config #5).

Covers: codebook round-trip error bounds, double-quant fidelity, pack/unpack
inversion, XLA dequant matmul vs full-precision reference, param-tree
quantize/dequantize transforms, and a tiny end-to-end QLoRA training run
(NF4 frozen base + LoRA adapters) with plain-safetensors export.

The fused Pallas kernel needs a real TPU (tests run on CPU); its numerics are
exercised here and by bench/infer
runs on hardware.
"""

import json
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llm_fine_tune_distributed_tpu.config import MeshConfig, TrainConfig
from llm_fine_tune_distributed_tpu.ops.nf4 import (
    NF4_CODEBOOK,
    dequantize_nf4,
    nf4_matmul,
    quantize_nf4,
    unpack_codes,
)
from llm_fine_tune_distributed_tpu.parallel.qlora import (
    dequantize_frozen,
    quantize_frozen,
    quantized_fraction,
)


def _j(q):
    return {k: jnp.asarray(v) for k, v in q.items()}


def test_pack_unpack_roundtrip():
    rng = np.random.RandomState(0)
    w = rng.randn(128, 64).astype(np.float32)
    q = quantize_nf4(w, block_size=64, double_quant=False)
    assert q["nf4"].shape == (16, 64) and q["nf4"].dtype == np.int32
    codes = np.asarray(unpack_codes(jnp.asarray(q["nf4"])))
    assert codes.shape == (128, 64)
    assert codes.min() >= 0 and codes.max() <= 15


def test_roundtrip_error_bounds():
    """Blockwise NF4: worst-case relative error within a block is bounded by
    half the largest codebook gap (~0.14 of the block absmax)."""
    rng = np.random.RandomState(1)
    w = rng.randn(256, 128).astype(np.float32)
    q = quantize_nf4(w, block_size=64, double_quant=False)
    deq = np.asarray(dequantize_nf4(_j(q), jnp.float32))
    gaps = np.diff(NF4_CODEBOOK)
    blocks = w.reshape(-1, 64, 128)
    absmax = np.abs(blocks).max(1, keepdims=True)
    bound = (gaps.max() / 2 + 1e-6) * absmax
    err = np.abs(deq.reshape(-1, 64, 128) - blocks)
    assert (err <= bound + 1e-6).all(), float((err - bound).max())


def test_double_quant_close_to_single():
    rng = np.random.RandomState(2)
    w = (rng.randn(512, 128) * rng.gamma(2.0, 1.0, (512, 128))).astype(np.float32)
    single = np.asarray(dequantize_nf4(_j(quantize_nf4(w, 64, False)), jnp.float32))
    double = np.asarray(dequantize_nf4(_j(quantize_nf4(w, 64, True)), jnp.float32))
    # int8 absmax quantization adds <1% relative error on the scales
    denom = np.abs(single).mean()
    assert np.abs(double - single).mean() / denom < 0.02
    q = quantize_nf4(w, 64, True)
    assert q["absmax_q"].dtype == np.int8
    # storage: 4 bits codes + 8 bits/block scales ≈ 4.13 bits/param total
    bits = (q["nf4"].nbytes + q["absmax_q"].nbytes + q["absmax_scale"].nbytes) * 8
    assert bits / w.size < 4.3


def test_nf4_matmul_xla_close_to_dense():
    rng = np.random.RandomState(3)
    w = rng.randn(512, 256).astype(np.float32)
    x = rng.randn(8, 512).astype(np.float32)
    q = _j(quantize_nf4(w, 64, True))
    y = np.asarray(nf4_matmul(jnp.asarray(x), q, impl="xla", compute_dtype=jnp.float32))
    deq = np.asarray(dequantize_nf4(q, jnp.float32))
    np.testing.assert_allclose(y, x @ deq, rtol=1e-4, atol=1e-3)
    # and the quantization error itself keeps the matmul in the right ballpark
    rel = np.abs(y - x @ w).mean() / np.abs(x @ w).mean()
    assert rel < 0.2, rel


def test_quantize_frozen_tree_and_inverse():
    rng = np.random.RandomState(4)
    frozen = {
        "model/layers/0/self_attn/q_proj/kernel": rng.randn(64, 64).astype(np.float32),
        "model/layers/0/mlp/down_proj/kernel": rng.randn(128, 64).astype(np.float32),
        "model/layers/0/input_layernorm/weight": np.ones((64,), np.float32),
        "model/embed_tokens/weight": rng.randn(512, 64).astype(np.float32),  # not /layers/
        "model/layers/0/self_attn/q_proj/lora_scale": np.float32(0.5),
    }
    q = quantize_frozen(frozen, block_size=64, double_quant=True)
    assert "model/layers/0/self_attn/q_proj/kernel_nf4" in q
    assert "model/layers/0/self_attn/q_proj/kernel" not in q
    assert "model/embed_tokens/weight" in q  # embeddings untouched
    assert "model/layers/0/input_layernorm/weight" in q
    # the two small kernels are NF4; the large untouched embedding dominates
    # total bytes, so the fraction is small but nonzero
    assert 0.0 < quantized_fraction(q) < 0.5

    back = dequantize_frozen({k: jnp.asarray(v) for k, v in q.items()}, jnp.float32)
    assert set(back) == set(frozen)
    orig = frozen["model/layers/0/mlp/down_proj/kernel"]
    rec = np.asarray(back["model/layers/0/mlp/down_proj/kernel"])
    assert np.abs(rec - orig).mean() / np.abs(orig).mean() < 0.1


def test_qlora_forward_matches_dequantized_dense():
    """A tiny model's forward through quantized frozen params must equal the
    forward through the explicitly dequantized dense params."""
    from llm_fine_tune_distributed_tpu.models.configs import get_preset
    from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params
    from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict, unflatten_dict

    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    flat = flatten_dict(params)
    qflat = quantize_frozen(flat, block_size=64, double_quant=True)
    deqflat = dequantize_frozen(qflat, jnp.float32)

    ids = jnp.asarray(np.random.RandomState(5).randint(0, 512, (2, 32)), jnp.int32)
    out_q, _ = forward(unflatten_dict(qflat), ids, mc, compute_dtype=jnp.float32,
                       quant_impl="xla", logits_dtype=jnp.float32)
    out_d, _ = forward(unflatten_dict(deqflat), ids, mc, compute_dtype=jnp.float32,
                       logits_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(out_q), np.asarray(out_d), rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_qlora_end_to_end(tmp_path):
    """QLoRA SFT on the 8-device mesh: NF4 frozen base + trainable adapters,
    loss decreases, export decodes back to plain safetensors."""
    from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    jsonl = tmp_path / "qa.jsonl"
    rng = np.random.RandomState(0)
    with open(jsonl, "w") as f:
        for i in range(64):
            f.write(json.dumps({
                "topic": "Knots",
                "question": f"question {i}?",
                "answer": f"answer {i}: " + " ".join(["word"] * int(rng.randint(3, 8))),
            }) + "\n")
    convert_jsonl_to_parquet(str(jsonl), str(tmp_path / "qa_dataset.parquet"), verbose=False)

    out = tmp_path / "outputs"
    config = TrainConfig(
        model_name="tiny-random",
        model_preset="tiny",
        tokenizer_path="byte-chatml",
        data_dir=str(tmp_path),
        dataset_file="qa_dataset.parquet",
        output_dir=str(out),
        freeze_strategy="qlora",
        lora_rank=4,
        epochs=2,
        per_device_batch_size=2,
        gradient_accumulation_steps=2,
        learning_rate=5e-3,
        max_seq_length=128,
        eval_steps=100,
        logging_steps=2,
        save_steps=100,
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=1),
        use_native_loader=False,
    )
    trainer = SFTTrainer(config)

    # frozen base is actually quantized
    assert any(k.endswith("kernel_nf4") for k in trainer.state.frozen)
    assert all(not k.endswith("/kernel") or "layers" not in k for k in trainer.state.frozen
               if "proj" in k), "block linears must be NF4, not dense"
    # only adapters train
    assert all(k.endswith(("lora_a", "lora_b")) for k in trainer.state.trainable)

    trainer.train()
    losses = [h["loss"] for h in trainer.metrics.history if "loss" in h]
    assert losses[-1] < losses[0], f"QLoRA loss did not decrease: {losses}"

    # exported model has plain kernels again (inference contract)
    from llm_fine_tune_distributed_tpu.models.hf_io import load_hf_checkpoint

    mc = trainer.model_config
    re_params = load_hf_checkpoint(str(out / "best_model"), mc, dtype=np.float32)
    flat = {k for k, _ in _tree_items(re_params)}
    assert any(k.endswith("q_proj/kernel") for k in flat)
    assert not any("nf4" in k for k in flat)


def _tree_items(tree, prefix=""):
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            yield from _tree_items(v, key)
        else:
            yield key, v


def test_jax_quantizer_matches_numpy():
    """The device-side quantizer (_quantize_codes_jax) must be bit-identical
    to the numpy path — CI runs CPU, so call the jitted fn directly."""
    from llm_fine_tune_distributed_tpu.ops.nf4 import _quantize_codes_jax

    rng = np.random.RandomState(7)
    w = rng.randn(256, 128).astype(np.float32)
    # numpy reference (the small-leaf path)
    ref = quantize_nf4(w, 64, double_quant=False)
    packed_j, absmax_j = _quantize_codes_jax(jnp.asarray(w), 64)
    np.testing.assert_array_equal(np.asarray(packed_j), np.asarray(ref["nf4"]))
    np.testing.assert_allclose(np.asarray(absmax_j), np.asarray(ref["absmax"]), rtol=1e-6)


def test_pallas_impl_is_retired():
    """The fused Pallas kernel was retired (lost the v5e shootout); asking
    for it errors with the pointer to the rationale."""
    q = quantize_nf4(jnp.ones((256, 128)), block_size=64)
    with pytest.raises(ValueError, match="retired"):
        nf4_matmul(jnp.ones((4, 256)), q, impl="pallas")


def test_layered_stacked_roundtrip():
    """4-D [L, E, in, out] pipe-stacked expert quantization (qlora x pipe x
    MoE, VERDICT r3 #4): per-layer slices are standalone stacked layouts and
    the roundtrip matches quantizing each layer independently."""
    from llm_fine_tune_distributed_tpu.ops.nf4 import (
        dequantize_nf4_layered_stacked,
        dequantize_nf4_stacked,
        quantize_nf4_layered_stacked,
        quantize_nf4_stacked,
        quantized_layout_layered_stacked,
    )

    rng = np.random.RandomState(2)
    w = rng.randn(2, 4, 64, 32).astype(np.float32)  # [L, E, in, out]
    q = quantize_nf4_layered_stacked(w, block_size=64, double_quant=True)
    assert q["nf4"].shape == (2, 4, 8, 32)
    assert q["absmax_q"].shape == (2, 4, 1, 32)
    assert q["absmax_scale"].ndim == 2 and q["absmax_scale"].shape[0] == 2
    assert q["absmax_offset"].shape == (2,)

    # the declared layout matches what the quantizer produced
    layout = quantized_layout_layered_stacked(w.shape, 64, True)
    for key, (shape, dtype) in layout.items():
        assert tuple(q[key].shape) == shape, key
        assert q[key].dtype == dtype, key

    deq = np.asarray(dequantize_nf4_layered_stacked(_j(q), jnp.float32))
    assert deq.shape == w.shape

    for i in range(2):
        # each layer slice is a complete standalone stacked layout — the
        # invariant the pipeline scan relies on (ops/moe consumes slices
        # with dequantize_nf4_stacked, never seeing the layer dim)
        per = quantize_nf4_stacked(w[i], block_size=64, double_quant=True)
        sliced = {k: jnp.asarray(v)[i] for k, v in q.items()}
        np.testing.assert_array_equal(
            np.asarray(sliced["nf4"]), np.asarray(per["nf4"])
        )
        np.testing.assert_allclose(
            np.asarray(dequantize_nf4_stacked(sliced, jnp.float32)),
            np.asarray(dequantize_nf4_stacked(_j(per), jnp.float32)),
            atol=1e-6,
        )
        np.testing.assert_allclose(deq[i], np.asarray(
            dequantize_nf4_stacked(_j(per), jnp.float32)), atol=1e-6)


def test_quantize_frozen_handles_pipe_stacked_experts():
    """quantize_frozen/dequantize_frozen round-trip the 4-D expert leaves the
    pipeline state carries, and the abstract planner agrees with the real
    quantizer leaf-for-leaf."""
    from llm_fine_tune_distributed_tpu.parallel.qlora import (
        quantize_frozen_abstract,
    )

    rng = np.random.RandomState(3)
    frozen = {
        "model/layers/@stacked/block_sparse_moe/experts/w1":
            rng.randn(2, 4, 64, 32).astype(np.float32),
        "model/layers/@stacked/block_sparse_moe/gate/kernel":
            rng.randn(2, 64, 4).astype(np.float32),
        "model/norm/weight": np.ones((64,), np.float32),
    }
    q = quantize_frozen(frozen, block_size=64)
    assert "model/layers/@stacked/block_sparse_moe/experts/w1_nf4" in q
    assert q["model/layers/@stacked/block_sparse_moe/experts/w1_nf4"].ndim == 4
    # router gate + norm pass through exact
    assert q["model/layers/@stacked/block_sparse_moe/gate/kernel"].shape == (2, 64, 4)

    abstract = quantize_frozen_abstract(
        {k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in frozen.items()},
        block_size=64,
    )
    assert set(abstract) == set(q)
    for k in q:
        assert tuple(abstract[k].shape) == tuple(np.shape(q[k])), k

    deq = dequantize_frozen(q, jnp.float32)
    assert set(deq) == set(frozen)
    w = frozen["model/layers/@stacked/block_sparse_moe/experts/w1"]
    err = np.abs(np.asarray(deq["model/layers/@stacked/block_sparse_moe/experts/w1"]) - w)
    assert err.mean() < 0.1  # NF4 quantization noise, not garbage
