"""ThroughputMeter: the steady-state window must exclude one-off pauses
(jit compile) that dominate the cumulative rate on short runs."""

import time

from llm_fine_tune_distributed_tpu.observe.throughput import ThroughputMeter


def test_steady_state_excludes_compile_pause():
    m = ThroughputMeter(2, tokens_per_sample=10)
    time.sleep(0.9)  # "compile" before the first step lands
    m.update(4)
    for _ in range(5):
        time.sleep(0.02)
        m.update(4)
    s = m.snapshot()
    assert "samples_per_second_per_chip_steady" in s
    # cumulative is dragged down by the 0.9s pause; steady (a median of
    # per-interval rates) is not. The margin tolerates the 0.02s sleeps
    # stretching ~10x on a loaded single-core box.
    assert s["samples_per_second_per_chip_steady"] > 1.5 * s["samples_per_second_per_chip"]
    assert s["samples_per_second_per_chip"] > 0
    assert s["tokens_per_second_per_chip"] > 0


def test_no_steady_metric_before_enough_steps():
    m = ThroughputMeter(1)
    m.update(2)
    assert "samples_per_second_per_chip_steady" not in m.snapshot()
    m.update(2)
    assert "samples_per_second_per_chip_steady" in m.snapshot()


def test_meter_multi_step_intervals():
    """Syncing only at log boundaries stamps multi-step intervals; rates and
    step counts stay correct because the window stores cumulative samples."""
    import time as _time

    from llm_fine_tune_distributed_tpu.observe.throughput import ThroughputMeter

    m = ThroughputMeter(n_chips=2)
    for _ in range(4):
        _time.sleep(0.01)
        m.update(8, steps=2)  # 2 steps' samples per stamp
    snap = m.snapshot()
    assert snap["steps_per_second"] > 0
    # 8 steps total, 32 samples
    assert abs(snap["samples_per_second"] / snap["steps_per_second"] - 4.0) < 1e-6
    assert "samples_per_second_per_chip_steady" in snap


def test_real_token_accounting():
    """real_tokens stamps produce the non-pad throughput + packing gauge;
    without stamps neither key appears (schema only grows when fed)."""
    m = ThroughputMeter(n_chips=2, tokens_per_sample=10)
    time.sleep(0.01)
    m.update(4, real_tokens=30)  # 40 padded slots, 30 real tokens
    time.sleep(0.01)
    m.update(4, real_tokens=30)
    s = m.snapshot()
    assert abs(s["packing_efficiency"] - 0.75) < 1e-9
    # real rate = padded rate x packing efficiency, per construction
    assert abs(
        s["real_tokens_per_second_per_chip"]
        - 0.75 * s["tokens_per_second_per_chip"]
    ) < 1e-6

    bare = ThroughputMeter(n_chips=1, tokens_per_sample=10)
    bare.update(4)
    s = bare.snapshot()
    assert "packing_efficiency" not in s
    assert "real_tokens_per_second_per_chip" not in s


def test_metric_logger_hparams(tmp_path):
    import json

    from llm_fine_tune_distributed_tpu.observe.metrics import MetricLogger

    m = MetricLogger(str(tmp_path))
    m.set_params({"learning_rate": 5e-5, "mesh": {"fsdp": 2}})
    m.log(1, 0.1, {"loss": 2.0})
    m.close()
    lines = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    assert lines[0]["hparams"]["learning_rate"] == 5e-5
    assert lines[1]["loss"] == 2.0
