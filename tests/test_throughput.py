"""ThroughputMeter: the steady-state window must exclude one-off pauses
(jit compile) that dominate the cumulative rate on short runs."""

import time

from llm_fine_tune_distributed_tpu.observe.throughput import ThroughputMeter


def test_steady_state_excludes_compile_pause():
    m = ThroughputMeter(2, tokens_per_sample=10)
    time.sleep(0.3)  # "compile" before the first step lands
    m.update(4)
    for _ in range(5):
        time.sleep(0.02)
        m.update(4)
    s = m.snapshot()
    assert "samples_per_second_per_chip_steady" in s
    # cumulative is dragged down by the 0.3s pause; steady is not
    assert s["samples_per_second_per_chip_steady"] > 2 * s["samples_per_second_per_chip"]
    assert s["samples_per_second_per_chip"] > 0
    assert s["tokens_per_second_per_chip"] > 0


def test_no_steady_metric_before_enough_steps():
    m = ThroughputMeter(1)
    m.update(2)
    assert "samples_per_second_per_chip_steady" not in m.snapshot()
    m.update(2)
    assert "samples_per_second_per_chip_steady" in m.snapshot()
