"""Fault injection: SIGKILL a training run mid-epoch, restart, resume.

The reference's fault-tolerance story is `restartPolicy: OnFailure` with
training restarting FROM SCRATCH (SURVEY.md §5.3 — nothing passes
resume_from_checkpoint). Here the claim is stronger: an abrupt kill (no
cleanup, no atexit) leaves a consistent Orbax checkpoint behind, and a
restart with RESUME_FROM_CHECKPOINT=latest continues from it — the JobSet
restart semantics, exercised for real."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(cfg_path, resume: bool):
    env = dict(os.environ)
    env.update(
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        JAX_PLATFORMS="cpu",
    )
    if resume:
        env["RESUME_FROM_CHECKPOINT"] = "latest"
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "training.py"),
         "--config", str(cfg_path), "--platform", "cpu"],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        bufsize=1,
    )


@pytest.mark.slow
@pytest.mark.slow
def test_sigkill_then_resume(tmp_path):
    from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet

    jsonl = tmp_path / "qa.jsonl"
    with open(jsonl, "w") as f:
        for i in range(64):
            f.write(json.dumps({
                "topic": "Knots",
                "question": f"question {i}?",
                "answer": f"answer {i}: " + "word " * (3 + i % 4),
            }) + "\n")
    convert_jsonl_to_parquet(str(jsonl), str(tmp_path / "qa_dataset.parquet"), verbose=False)

    out = tmp_path / "outputs"
    cfg = {
        "model_name": "tiny-random",
        "model_preset": "tiny",
        "tokenizer_path": "byte-chatml",
        "system_prompt": "You are an expert.",
        "data_dir": str(tmp_path),
        "dataset_file": "qa_dataset.parquet",
        "output_dir": str(out),
        "epochs": 2,
        "per_device_batch_size": 2,
        "gradient_accumulation_steps": 1,
        "learning_rate": 2e-3,
        "max_seq_length": 128,
        "eval_steps": 100,
        "logging_steps": 1,
        "save_steps": 3,  # checkpoint frequently so the kill lands after one
        "mesh": {"data": 1, "fsdp": 2, "tensor": 1, "seq": 1},
        "use_native_loader": False,
    }
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))

    # ---- phase 1: run, then SIGKILL once a checkpoint exists
    proc = _launch(cfg_path, resume=False)
    killed_after_step = None
    deadline = time.time() + 420
    for line in proc.stdout:
        if "step=" in line:
            step = int(line.split("step=")[1].split(",")[0])
            ckpt_dir = out / "checkpoints"
            have_ckpt = ckpt_dir.exists() and any(
                d.isdigit() for d in os.listdir(ckpt_dir)
            )
            if step >= 4 and have_ckpt:
                killed_after_step = step
                proc.send_signal(signal.SIGKILL)
                break
        if time.time() > deadline:
            proc.kill()
            pytest.fail("phase 1 never reached a checkpointed step")
    proc.wait(timeout=60)
    assert killed_after_step is not None
    assert proc.returncode != 0, "process should have died from SIGKILL"
    assert not (out / "training_summary.json").exists(), "no clean finish expected"

    # ---- phase 2: restart with resume
    proc2 = _launch(cfg_path, resume=True)
    stdout, _ = proc2.communicate(timeout=420)
    assert proc2.returncode == 0, f"resume run failed:\n{stdout[-4000:]}"
    assert "Resumed from checkpoint step" in stdout
    resumed_step = int(stdout.split("Resumed from checkpoint step")[1].split()[0])
    assert 0 < resumed_step <= killed_after_step

    # clean completion with the artifact contract
    assert (out / "training_summary.json").exists()
    assert (out / "best_model" / "model.safetensors").exists()
    history = json.loads((out / "training_history.json").read_text())
    steps = [h["step"] for h in history if "step" in h]
    # phase 2 history starts after the resume point (no step trained twice
    # within this run) and reaches the end of epoch 2
    assert steps and steps[0] > resumed_step


def test_preemption_flag_checkpoints_and_returns(tmp_path):
    """request_preemption(): the loop stops at the NEXT step boundary,
    writes an emergency checkpoint (off the save_steps cadence), and
    train() returns a reduced summary — and a resumed run picks up exactly
    after the preempted step."""
    from test_train_e2e import make_config, qa_parquet  # noqa: F401

    from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    jsonl = tmp_path / "qa.jsonl"
    with open(jsonl, "w") as f:
        for i in range(48):
            f.write(json.dumps({
                "topic": "Knots",
                "question": f"question {i}?",
                "answer": f"answer {i}: word word word",
            }) + "\n")
    convert_jsonl_to_parquet(
        str(jsonl), str(tmp_path / "qa_dataset.parquet"), verbose=False
    )
    out = tmp_path / "out"
    cfg = make_config(
        out, tmp_path, "qa_dataset.parquet", epochs=1, eval_steps=0,
        logging_steps=1, save_steps=100,  # cadence never fires: the only
        use_native_loader=False,          # checkpoint is the emergency one
    )
    trainer = SFTTrainer(cfg)
    trainer.request_preemption()  # preempt before the loop: stops at step 1
    summary = trainer.train()
    assert summary["preempted"] is True
    assert summary["step"] == 1

    from llm_fine_tune_distributed_tpu.train.checkpoints import CheckpointManager

    ckpt = CheckpointManager(str(out / "checkpoints"))
    assert ckpt.latest_step == 1  # emergency save, off the 100-step cadence
    ckpt.close()

    resume_cfg = make_config(
        out, tmp_path, "qa_dataset.parquet", epochs=1, eval_steps=0,
        logging_steps=1, save_steps=100, use_native_loader=False,
        resume_from_checkpoint="latest",
    )
    resumed = SFTTrainer(resume_cfg)
    summary2 = resumed.train()
    assert "preempted" not in summary2  # ran to completion this time
    steps = [h["step"] for h in resumed.metrics.history if "step" in h]
    assert steps and steps[0] == 2  # no step trained twice


@pytest.mark.slow
def test_sigterm_drains_to_checkpoint_and_exits_zero(tmp_path):
    """SIGTERM mid-training (the JobSet drain signal): the run writes an
    emergency checkpoint at the step boundary and exits 0 — then a restart
    with resume continues from that exact step."""
    from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet

    jsonl = tmp_path / "qa.jsonl"
    with open(jsonl, "w") as f:
        for i in range(64):
            f.write(json.dumps({
                "topic": "Knots",
                "question": f"question {i}?",
                "answer": f"answer {i}: " + "word " * (3 + i % 4),
            }) + "\n")
    convert_jsonl_to_parquet(
        str(jsonl), str(tmp_path / "qa_dataset.parquet"), verbose=False
    )
    out = tmp_path / "outputs"
    cfg = {
        "model_name": "tiny-random",
        "model_preset": "tiny",
        "tokenizer_path": "byte-chatml",
        "system_prompt": "You are an expert.",
        "data_dir": str(tmp_path),
        "dataset_file": "qa_dataset.parquet",
        "output_dir": str(out),
        "epochs": 2,
        "per_device_batch_size": 2,
        "gradient_accumulation_steps": 1,
        "learning_rate": 2e-3,
        "max_seq_length": 128,
        "eval_steps": 100,
        "logging_steps": 1,
        "save_steps": 100,  # cadence never fires before the signal lands
        "mesh": {"data": 1, "fsdp": 2, "tensor": 1, "seq": 1},
        "use_native_loader": False,
    }
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))

    # ---- phase 1: run a few steps, then SIGTERM (graceful, unlike SIGKILL)
    proc = _launch(cfg_path, resume=False)
    deadline = time.time() + 420
    lines = []
    for line in proc.stdout:
        lines.append(line)
        if "step=" in line:
            step = int(line.split("step=")[1].split(",")[0])
            if step >= 3:
                proc.send_signal(signal.SIGTERM)
                break
        if time.time() > deadline:
            proc.kill()
            pytest.fail("phase 1 never reached step 3")
    rest, _ = proc.communicate(timeout=180)
    lines.append(rest)
    output = "".join(lines)
    assert proc.returncode == 0, f"SIGTERM exit was not clean:\n{output[-4000:]}"
    assert "preempted at step" in output
    ckpts = os.listdir(out / "checkpoints")
    assert any(d.isdigit() for d in ckpts), ckpts

    # ---- phase 2: restart with resume continues from the emergency save
    proc2 = _launch(cfg_path, resume=True)
    stdout, _ = proc2.communicate(timeout=420)
    assert proc2.returncode == 0, f"resume run failed:\n{stdout[-4000:]}"
    assert "Resumed from checkpoint step" in stdout
    resumed_step = int(stdout.split("Resumed from checkpoint step")[1].split()[0])
    assert resumed_step >= 3  # the emergency save, not an earlier cadence one
    assert (out / "training_summary.json").exists()
