"""Fault injection: SIGKILL a training run mid-epoch, restart, resume.

The reference's fault-tolerance story is `restartPolicy: OnFailure` with
training restarting FROM SCRATCH (SURVEY.md §5.3 — nothing passes
resume_from_checkpoint). Here the claim is stronger: an abrupt kill (no
cleanup, no atexit) leaves a consistent Orbax checkpoint behind, and a
restart with RESUME_FROM_CHECKPOINT=latest continues from it — the JobSet
restart semantics, exercised for real."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(cfg_path, resume: bool):
    env = dict(os.environ)
    env.update(
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        JAX_PLATFORMS="cpu",
    )
    if resume:
        env["RESUME_FROM_CHECKPOINT"] = "latest"
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "training.py"),
         "--config", str(cfg_path), "--platform", "cpu"],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        bufsize=1,
    )


@pytest.mark.slow
@pytest.mark.slow
def test_sigkill_then_resume(tmp_path):
    from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet

    jsonl = tmp_path / "qa.jsonl"
    with open(jsonl, "w") as f:
        for i in range(64):
            f.write(json.dumps({
                "topic": "Knots",
                "question": f"question {i}?",
                "answer": f"answer {i}: " + "word " * (3 + i % 4),
            }) + "\n")
    convert_jsonl_to_parquet(str(jsonl), str(tmp_path / "qa_dataset.parquet"), verbose=False)

    out = tmp_path / "outputs"
    cfg = {
        "model_name": "tiny-random",
        "model_preset": "tiny",
        "tokenizer_path": "byte-chatml",
        "system_prompt": "You are an expert.",
        "data_dir": str(tmp_path),
        "dataset_file": "qa_dataset.parquet",
        "output_dir": str(out),
        "epochs": 2,
        "per_device_batch_size": 2,
        "gradient_accumulation_steps": 1,
        "learning_rate": 2e-3,
        "max_seq_length": 128,
        "eval_steps": 100,
        "logging_steps": 1,
        "save_steps": 3,  # checkpoint frequently so the kill lands after one
        "mesh": {"data": 1, "fsdp": 2, "tensor": 1, "seq": 1},
        "use_native_loader": False,
    }
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))

    # ---- phase 1: run, then SIGKILL once a checkpoint exists
    proc = _launch(cfg_path, resume=False)
    killed_after_step = None
    deadline = time.time() + 420
    for line in proc.stdout:
        if "step=" in line:
            step = int(line.split("step=")[1].split(",")[0])
            ckpt_dir = out / "checkpoints"
            have_ckpt = ckpt_dir.exists() and any(
                d.isdigit() for d in os.listdir(ckpt_dir)
            )
            if step >= 4 and have_ckpt:
                killed_after_step = step
                proc.send_signal(signal.SIGKILL)
                break
        if time.time() > deadline:
            proc.kill()
            pytest.fail("phase 1 never reached a checkpointed step")
    proc.wait(timeout=60)
    assert killed_after_step is not None
    assert proc.returncode != 0, "process should have died from SIGKILL"
    assert not (out / "training_summary.json").exists(), "no clean finish expected"

    # ---- phase 2: restart with resume
    proc2 = _launch(cfg_path, resume=True)
    stdout, _ = proc2.communicate(timeout=420)
    assert proc2.returncode == 0, f"resume run failed:\n{stdout[-4000:]}"
    assert "Resumed from checkpoint step" in stdout
    resumed_step = int(stdout.split("Resumed from checkpoint step")[1].split()[0])
    assert 0 < resumed_step <= killed_after_step

    # clean completion with the artifact contract
    assert (out / "training_summary.json").exists()
    assert (out / "best_model" / "model.safetensors").exists()
    history = json.loads((out / "training_history.json").read_text())
    steps = [h["step"] for h in history if "step" in h]
    # phase 2 history starts after the resume point (no step trained twice
    # within this run) and reaches the end of epoch 2
    assert steps and steps[0] > resumed_step
