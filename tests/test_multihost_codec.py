"""Control-header codecs for multi-host serving (infer/multihost.py).

The window protocol's GenerationConfig codec and the slot-engine tick
protocol's knob/manifest codecs are pure host-side byte shuffling — this
pins them without any mesh: ``_encode_cfg`` overflow raises cleanly (not a
truncated broadcast), every GenerationConfig field round-trips exactly,
the header shape is FIXED across configs (a shape that varied per config
would desynchronize the fleet's broadcasts), and the slot bridge's knob
vector and sized-tree manifests reconstruct their inputs bit-for-bit.
"""

import dataclasses

import numpy as np
import pytest

from llm_fine_tune_distributed_tpu.infer.multihost import (
    _CFG_BUF,
    _HEADER_LEN,
    _KNOB_FIELDS,
    _SLOT_HEADER_LEN,
    _decode_cfg,
    _decode_knobs,
    _encode_cfg,
    _encode_knobs,
    _manifest_entries,
    _tree_manifest,
)
from llm_fine_tune_distributed_tpu.infer.sampling import GenerationConfig

# ------------------------------------------------------- window cfg codec


def test_cfg_roundtrips_every_field():
    """Every GenerationConfig field survives the JSON wire — including
    non-default values for ALL fields at once, so a field added to the
    dataclass without codec support fails here, not on a pod."""
    fields = {f.name: f.default for f in dataclasses.fields(GenerationConfig)}
    overrides = {}
    for name, default in fields.items():
        if isinstance(default, bool):
            overrides[name] = not default
        elif isinstance(default, int):
            overrides[name] = default + 3
        elif isinstance(default, float):
            overrides[name] = default * 0.5 + 0.125
    gen = GenerationConfig(**overrides)
    buf, length = _encode_cfg(gen)
    assert _decode_cfg(buf, length) == gen


def test_cfg_default_roundtrip_and_fixed_buffer_shape():
    g1 = GenerationConfig()
    g2 = GenerationConfig(max_new_tokens=999, temperature=0.123, top_k=7)
    b1, l1 = _encode_cfg(g1)
    b2, l2 = _encode_cfg(g2)
    # the BUFFER shape never varies with the config — only the length
    # prefix in the header does (fixed-shape broadcasts or deadlock)
    assert b1.shape == b2.shape == (_CFG_BUF,)
    assert b1.dtype == b2.dtype == np.uint8
    assert _decode_cfg(b1, l1) == g1
    assert _decode_cfg(b2, l2) == g2


def test_cfg_overflow_raises_cleanly():
    # an oversized field value must fail the encode with a clear
    # ValueError, never silently truncate the buffer (replace() performs
    # no type checking, so this models a pathological client string)
    huge = dataclasses.replace(GenerationConfig(), top_k="x" * (_CFG_BUF + 1))
    with pytest.raises(ValueError, match=str(_CFG_BUF)):
        _encode_cfg(huge)


def test_header_lengths_are_constants():
    # wire-format freeze: bumping either is a protocol break that needs
    # every host on the same build — make the bump loud
    assert _HEADER_LEN == 5
    assert _SLOT_HEADER_LEN == 10


# ----------------------------------------------------- slot bridge codecs


def test_knob_vector_roundtrips_exactly():
    knobs = {
        "temperature": np.float32(0.7),
        "top_p": np.float32(0.95),
        "top_k": np.int32(40),
        "repetition_penalty": np.float32(1.1),
        "do_sample": np.bool_(True),
        "adapter_idx": np.int32(3),
    }
    vec = _encode_knobs(knobs)
    assert vec.shape == (len(_KNOB_FIELDS),) and vec.dtype == np.float64
    out = _decode_knobs(vec)
    for field in _KNOB_FIELDS:
        assert out[field] == knobs[field]
        assert out[field].dtype == knobs[field].dtype


def test_knob_vector_shape_fixed_across_values():
    a = _encode_knobs(
        {
            "temperature": 1.0, "top_p": 1.0, "top_k": 0,
            "repetition_penalty": 1.0, "do_sample": False, "adapter_idx": 0,
        }
    )
    b = _encode_knobs(
        {
            "temperature": 0.1, "top_p": 0.5, "top_k": 512,
            "repetition_penalty": 1.3, "do_sample": True, "adapter_idx": 7,
        }
    )
    assert a.shape == b.shape


def test_tree_manifest_roundtrips_shapes_dtypes_order():
    tree = {
        "model/layers/0/self_attn/q_proj/kernel": np.arange(12, dtype=np.float32).reshape(3, 4),
        "model/embed_tokens/weight": np.ones((2, 2), np.int8),
        "a/scalarish": np.asarray([1.5], np.float64),
    }
    manifest, entries = _tree_manifest(tree)
    assert manifest.dtype == np.uint8
    decoded = _manifest_entries(manifest)
    # sorted path order, shape and dtype preserved
    assert [p for p, _, _ in decoded] == sorted(tree)
    for (path, shape, dtype), (spath, arr) in zip(decoded, entries):
        assert path == spath
        assert shape == arr.shape and dtype == arr.dtype
        np.testing.assert_array_equal(arr, tree[path])
