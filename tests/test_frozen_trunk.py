"""Frozen-trunk fast path (TrainConfig.frozen_compute="int8"): the w8a8
op, the trainable-boundary rule, numeric parity against the bf16 default,
and — the guard the feature stands on — backward DCE: the trunk's backward
must be ABSENT from the compiled step. The compile-cost test below fails
if trunk backward/recompute ever reappears (a remat-scope regression, a
stop_gradient moved) and the lowered-text test fails if the trunk stops
lowering to int8 dot_generals (a dequant-then-bf16-matmul regression).

On-TPU speedup is gated by bench.py's BENCH_FROZEN_INT8_GUARD arm; here
(CPU tier-1) the gates are numeric parity (interpret == XLA bit-exact,
int8 trunk close to bf16) and program structure.
"""

import json
import os
import re
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_fine_tune_distributed_tpu.config import TrainConfig
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params
from llm_fine_tune_distributed_tpu.ops.int8 import quantize_int8
from llm_fine_tune_distributed_tpu.ops.int8_matmul import (
    int8_w8a8_matmul,
    quantize_rows_int8,
)
from llm_fine_tune_distributed_tpu.parallel.freeze import (
    frozen_trunk_boundary,
    quantize_trunk_int8,
    trainable_mask,
)
from llm_fine_tune_distributed_tpu.train.step import build_train_step, make_loss_fn
from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict

MC = get_preset("tiny")
SEQ, BATCH = 32, 4

# matches the int8 contraction in pre-optimization StableHLO ("dot_general
# ... tensor<...xi8>"); the compiled HLO is useless for this — CPU XLA
# rewrites s8 dots as convert+s32 and fuses the converts away
_I8_DOT_RE = re.compile(r"dot_general[^\n]*tensor<[0-9x]*xi8>")
# 7 projections per layer (q/k/v/o + gate/up/down)
_PROJECTIONS_PER_LAYER = 7


def _tiny_state(frozen_compute):
    """(trainable, frozen, train_config, frozen_layers) on the tiny preset,
    f32 params, default last_n_and_head freezing (trunk = 2 of 4 layers)."""
    tc = TrainConfig(
        model_preset="tiny",
        compute_dtype="float32",
        frozen_compute=frozen_compute,
        gradient_checkpointing=True,
        per_device_batch_size=BATCH,
        gradient_accumulation_steps=1,
        max_seq_length=SEQ,
    )
    params = init_params(jax.random.PRNGKey(0), MC, dtype=jnp.float32)
    mask = trainable_mask(params, MC, tc)
    flat_mask = flatten_dict(mask)
    boundary = 0
    flat = flatten_dict(params)
    trainable = {k: v for k, v in flat.items() if flat_mask[k]}
    frozen = {k: v for k, v in flat.items() if not flat_mask[k]}
    if frozen_compute == "int8":
        boundary = frozen_trunk_boundary(flat_mask, MC.num_layers)
        frozen, _ = quantize_trunk_int8(frozen, boundary)
    return trainable, frozen, tc, boundary


def _batch(accum=1):
    rng = np.random.RandomState(3)
    ids = rng.randint(0, MC.vocab_size, (accum, BATCH, SEQ)).astype(np.int32)
    return {
        "input_ids": jnp.asarray(ids),
        "loss_mask": jnp.ones((accum, BATCH, SEQ), jnp.float32),
        "attention_mask": jnp.ones((accum, BATCH, SEQ), jnp.int32),
    }


# ------------------------------------------------------------------ the op


def test_quantize_rows_int8():
    x = jnp.asarray(np.random.RandomState(0).randn(5, 64), jnp.float32)
    codes, scale = quantize_rows_int8(x)
    assert codes.dtype == jnp.int8 and scale.shape == (5,)
    # absmax-symmetric: dequant error bounded by half a quantization step
    deq = codes.astype(jnp.float32) * scale[:, None]
    assert float(jnp.max(jnp.abs(deq - x))) <= float(jnp.max(scale)) * 0.5 + 1e-6
    # all-zero rows: scale 1.0, zero codes, exact-zero dequant
    z_codes, z_scale = quantize_rows_int8(jnp.zeros((2, 8)))
    assert float(jnp.max(jnp.abs(z_codes))) == 0.0
    assert np.allclose(np.asarray(z_scale), 1.0 / 127.0)


def test_w8a8_interpret_matches_xla_bitwise():
    """The Pallas kernel (interpret mode on CPU) and the XLA dot_general
    compute the SAME int32 accumulation and f32 rescale — bit-identical,
    which is what lets the CPU tier run the kernel's math at all."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(16, 64), jnp.float32)
    q = quantize_int8(jnp.asarray(rng.randn(64, 48), jnp.float32))
    q = {"int8": q["int8"], "int8_scale": q["int8_scale"]}
    out_xla = int8_w8a8_matmul(x, q, jnp.float32, impl="xla")
    out_interp = int8_w8a8_matmul(x, q, jnp.float32, impl="interpret")
    assert np.array_equal(np.asarray(out_xla), np.asarray(out_interp))


def test_w8a8_close_to_f32_reference():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(8, 64), jnp.float32)
    w = jnp.asarray(rng.randn(64, 32), jnp.float32)
    ref = x @ w
    out = int8_w8a8_matmul(x, quantize_int8(w), jnp.float32, impl="xla")
    rel = float(jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref))
    assert rel < 0.05  # two 8-bit absmax roundings


def test_w8a8_rejects_unknown_impl():
    x = jnp.ones((2, 8))
    q = quantize_int8(jnp.ones((8, 4)))
    with pytest.raises(ValueError, match="unknown trunk matmul impl"):
        int8_w8a8_matmul(x, q, impl="cuda")


# ------------------------------------------------------------- the boundary


def test_boundary_last_n_and_head():
    # default unfreeze_last_n_layers=2 on the 4-layer tiny: trunk = [0, 2)
    _, _, _, boundary = _tiny_state("int8")
    assert boundary == MC.num_layers - 2


def test_boundary_lora_and_full_have_no_trunk():
    params = init_params(jax.random.PRNGKey(0), MC, dtype=jnp.float32)
    for strategy in ("lora", "none"):
        tc = TrainConfig(model_preset="tiny", freeze_strategy=strategy)
        p = params
        if strategy == "lora":
            from llm_fine_tune_distributed_tpu.parallel.lora import (
                add_lora_from_config,
            )

            p = add_lora_from_config(params, jax.random.PRNGKey(1), tc)
        flat_mask = flatten_dict(trainable_mask(p, MC, tc))
        assert frozen_trunk_boundary(flat_mask, MC.num_layers) == 0, strategy


def test_quantize_trunk_covers_exactly_the_trunk_projections():
    _, frozen, _, boundary = _tiny_state("int8")
    int8_keys = [k for k in frozen if k.endswith("/kernel_int8")]
    assert len(int8_keys) == boundary * _PROJECTIONS_PER_LAYER
    for k in int8_keys:
        layer = int(re.search(r"model/layers/(\d+)/", k).group(1))
        assert layer < boundary
        assert f"{k}_scale" in frozen  # per-channel scale sibling
    # norms stay full precision (plain weight leaves, never quantized)
    assert any(k.endswith("input_layernorm/weight") for k in frozen)


def test_make_loss_fn_rejects_unknown_frozen_compute():
    tc = TrainConfig(model_preset="tiny", frozen_compute="fp8")
    with pytest.raises(ValueError, match="unknown frozen_compute"):
        make_loss_fn(MC, tc)


# ----------------------------------------------------------------- parity


def _grad_fn(frozen_compute):
    trainable, frozen, tc, boundary = _tiny_state(frozen_compute)
    loss_fn = make_loss_fn(MC, tc, frozen_layers=boundary)
    gfn = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))
    b = _batch()
    batch = {k: v[0] for k, v in b.items()}
    return gfn, trainable, frozen, batch


def test_int8_loss_and_grads_parity_with_bf16_path():
    """int8 trunk ~ the full-precision path: loss within the 8-bit rounding
    band, gradients present for every trainable leaf and nonzero."""
    gfn_ref, trainable, frozen_ref, batch = _grad_fn("bf16")
    (loss_ref, _), _ = gfn_ref(trainable, frozen_ref, batch)
    gfn_i8, trainable, frozen_i8, batch = _grad_fn("int8")
    (loss_i8, _), grads = gfn_i8(trainable, frozen_i8, batch)
    assert abs(float(loss_i8) - float(loss_ref)) < 0.02 * float(loss_ref)
    for k, g in grads.items():
        assert float(jnp.max(jnp.abs(g))) > 0.0, f"dead gradient for {k}"


def test_int8_train_loss_curve_tracks_bf16():
    """5 optimizer steps on identical synthetic batches: the int8-trunk loss
    curve must track the full-precision curve within a tight relative band
    (the trunk only perturbs the forward; the trainable update rule is
    identical)."""
    from llm_fine_tune_distributed_tpu.parallel.optimizer import build_optimizer
    from llm_fine_tune_distributed_tpu.train.state import TrainState
    from llm_fine_tune_distributed_tpu.train.step import jit_train_step

    def run(frozen_compute):
        trainable, frozen, tc, boundary = _tiny_state(frozen_compute)
        opt = build_optimizer(tc, None, total_steps=5, data_parallel_size=1)
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            trainable=trainable,
            frozen=frozen,
            opt_state=opt.init(trainable),
        )
        step_fn = jit_train_step(
            build_train_step(MC, tc, opt, frozen_layers=boundary)
        )
        batch = _batch(accum=1)
        losses = []
        for _ in range(5):
            state, metrics = step_fn(state, batch)
            losses.append(float(metrics["loss"]))
        return losses

    ref, i8 = run("bf16"), run("int8")
    assert ref[-1] < ref[0]  # both actually learn
    assert i8[-1] < i8[0]
    for a, b in zip(ref, i8):
        assert abs(a - b) < 0.02 * abs(a), (ref, i8)


# ------------------------------------------------------- backward-DCE guard


def _lower(frozen_compute):
    trainable, frozen, tc, boundary = _tiny_state(frozen_compute)
    loss_fn = make_loss_fn(MC, tc, frozen_layers=boundary)
    gfn = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))
    b = _batch()
    batch = {k: v[0] for k, v in b.items()}
    return gfn.lower(trainable, frozen, batch), boundary


def test_trunk_lowers_to_int8_dot_generals():
    """Exactly the frozen-block projections contract in int8 — counted in
    the pre-optimization StableHLO, where the i8 operand types survive."""
    lowered, boundary = _lower("int8")
    n_i8 = len(_I8_DOT_RE.findall(lowered.as_text()))
    assert n_i8 == boundary * _PROJECTIONS_PER_LAYER, n_i8
    lowered_ref, _ = _lower("bf16")
    assert not _I8_DOT_RE.findall(lowered_ref.as_text())


def test_backward_dce_compile_cost_guard():
    """THE guard: the int8-trunk grad program must cost meaningfully fewer
    FLOPs than the bf16 default, because the trunk pays forward-only (its
    backward + remat recompute are DCE'd past the boundary stop_gradient).
    Measured ratio on tiny is ~0.80; a ratio near 1.0 means trunk backward
    or recompute reappeared. cost_analysis comes from the REAL compiled
    step (the same signal CompileLedger records on TPU)."""

    def flops(frozen_compute):
        lowered, _ = _lower(frozen_compute)
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca["flops"])

    ratio = flops("int8") / flops("bf16")
    assert ratio < 0.9, f"trunk backward appears to be back: ratio={ratio:.3f}"


# ----------------------------------------------------- CPU bench smoke arm


def test_bench_smoke_int8_interpret(tmp_path):
    """bench.py end-to-end on the CPU fallback recipe with the int8 trunk
    on the INTERPRET path — tier-1 coverage of the Pallas kernel inside the
    real jitted train step, plus the bench JSON contract (mfu /
    trunk_flops_fraction / frozen_compute fields)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        BENCH_FROZEN_COMPUTE="int8",
        TRUNK_MATMUL="interpret",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["metric"] == "sft_samples_per_sec_per_chip"
    assert result["frozen_compute"] == "int8"
    assert result["value"] > 0
    assert 0.0 < result["trunk_flops_fraction"] < 1.0
    assert "mfu" in result  # 0.0 on CPU (no roofline), present by contract
