"""Mixture-of-experts: routing math against a per-token reference loop,
expert-parallel sharding parity, aux-loss behavior, end-to-end train-step
convergence, and the Mixtral-8x7B abstract trace.

The reference is dense-only (SURVEY.md §2.4: EP absent); ops/moe.py extends
the framework to the Mixtral family with GShard-style einsum dispatch."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_fine_tune_distributed_tpu.config import MeshConfig, ModelConfig, TrainConfig
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.ops.moe import expert_capacity, init_moe_params, moe_mlp
from llm_fine_tune_distributed_tpu.parallel.diagnostics import assert_seq_parallel


def _cfg(**kw):
    base = dict(
        name="t",
        vocab_size=128,
        hidden_size=16,
        intermediate_size=32,
        num_layers=1,
        num_heads=2,
        num_kv_heads=2,
        num_experts=4,
        num_experts_per_tok=2,
        capacity_factor=8.0,  # big: no drops unless a test wants them
    )
    base.update(kw)
    return ModelConfig(**base)


def _reference_moe(lp, x, config):
    """Per-token numpy loop: top-k renormalized routing, no capacity."""
    b, s, h = x.shape
    gate = np.asarray(lp["gate"]["kernel"], np.float32)
    w1 = np.asarray(lp["experts"]["w1"], np.float32)
    w2 = np.asarray(lp["experts"]["w2"], np.float32)
    w3 = np.asarray(lp["experts"]["w3"], np.float32)
    y = np.zeros_like(np.asarray(x, np.float32))
    for bi in range(b):
        for si in range(s):
            t = np.asarray(x[bi, si], np.float32)
            logits = t @ gate
            p = np.exp(logits - logits.max())
            p /= p.sum()
            top = np.argsort(-p)[: config.num_experts_per_tok]
            w = p[top] / p[top].sum()
            for e, we in zip(top, w):
                hidden = (t @ w1[e]) * (1 / (1 + np.exp(-(t @ w1[e])))) * (t @ w3[e])
                y[bi, si] += we * (hidden @ w2[e])
    return y


def test_moe_matches_reference_loop():
    config = _cfg()
    lp = init_moe_params(jax.random.PRNGKey(0), config, jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16), jnp.float32)
    y, aux = jax.jit(lambda lp, x: moe_mlp(lp, x, config, jnp.float32))(lp, x)
    ref = _reference_moe(lp, x, config)
    np.testing.assert_allclose(np.asarray(y), ref, atol=1e-5)
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_capacity_drops_overflow_tokens():
    """With capacity 1 per (row, expert), later tokens routed to a full
    expert are dropped — output attenuates but stays finite."""
    config = _cfg(capacity_factor=1e-6)  # floor -> cap = 1
    assert expert_capacity(16, config) == 1
    lp = init_moe_params(jax.random.PRNGKey(0), config, jnp.float32)
    x = jnp.asarray(np.random.RandomState(1).randn(1, 16, 16), jnp.float32)
    y, aux = jax.jit(lambda lp, x: moe_mlp(lp, x, config, jnp.float32))(lp, x)
    full = _reference_moe(lp, x, config)
    y = np.asarray(y)
    assert np.all(np.isfinite(y))
    # 16 tokens x k=2 = 32 assignments compete for 4 expert slots: most
    # tokens are FULLY dropped (exact-zero output rows)
    zero_rows = np.abs(y[0]).sum(-1) == 0
    assert zero_rows.sum() >= 8
    # the first token wins position 0 in both of its experts' queues, so it
    # is never dropped and matches the capacity-free reference exactly
    np.testing.assert_allclose(y[0, 0], full[0, 0], atol=1e-5)


def test_uniform_router_aux_is_one():
    """A perfectly uniform router gives aux = 1.0 (the minimum)."""
    config = _cfg()
    lp = init_moe_params(jax.random.PRNGKey(0), config, jnp.float32)
    lp["gate"]["kernel"] = jnp.zeros_like(lp["gate"]["kernel"])  # uniform probs
    x = jnp.asarray(np.random.RandomState(2).randn(2, 32, 16), jnp.float32)
    _, aux = moe_mlp(lp, x, config, jnp.float32)
    # top-k tie-breaking still dispatches k of E experts; probs are uniform
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)


def test_expert_parallel_matches_unsharded(eight_devices):
    """moe_mlp under an expert=4 mesh == the single-device result."""
    config = _cfg()
    lp = init_moe_params(jax.random.PRNGKey(0), config, jnp.float32)
    x = jnp.asarray(np.random.RandomState(3).randn(4, 8, 16), jnp.float32)
    ref, aux_ref = moe_mlp(lp, x, config, jnp.float32)

    mesh = Mesh(
        np.array(eight_devices).reshape(2, 1, 1, 1, 4),
        ("data", "fsdp", "tensor", "seq", "expert"),
    )
    from llm_fine_tune_distributed_tpu.parallel.sharding import shard_params

    # rules match on the full path, so shard under the real subtree name
    lp_sharded = shard_params({"block_sparse_moe": lp}, mesh)["block_sparse_moe"]
    x_sharded = jax.device_put(x, NamedSharding(mesh, P(("data", "fsdp"))))
    y, aux = jax.jit(
        lambda lp, x: moe_mlp(lp, x, config, jnp.float32, mesh=mesh)
    )(lp_sharded, x_sharded)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_forward_tiny_moe_and_aux():
    from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params

    config = get_preset("tiny_moe")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 512, (2, 16)), jnp.int32)
    logits, _, aux = forward(
        params, ids, config, compute_dtype=jnp.float32, return_aux=True
    )
    assert logits.shape == (2, 16, 512)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) > 0  # 2 MoE layers contribute


@pytest.mark.slow
def test_moe_train_step_converges():
    """Loss (CE + aux) decreases over a few steps on tiny_moe."""
    from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
    from llm_fine_tune_distributed_tpu.parallel.optimizer import build_optimizer
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.train.state import TrainState
    from llm_fine_tune_distributed_tpu.train.step import build_train_step
    from llm_fine_tune_distributed_tpu.utils.tree import split_by_mask

    config = get_preset("tiny_moe")
    tc = TrainConfig(
        model_preset="tiny_moe",
        per_device_batch_size=4,
        gradient_accumulation_steps=1,
        max_seq_length=32,
        learning_rate=5e-3,
        freeze_strategy="none",
        gradient_checkpointing=False,
        attention_impl="xla",
    )
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    mask = trainable_mask(params, config, tc)
    trainable, frozen = split_by_mask(params, mask)
    optimizer = build_optimizer(tc, None, total_steps=20, data_parallel_size=1)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        trainable=trainable,
        frozen=frozen,
        opt_state=optimizer.init(trainable),
    )
    step = jax.jit(build_train_step(config, tc, optimizer))
    rng = np.random.RandomState(0)
    batch = {
        "input_ids": jnp.asarray(rng.randint(0, 512, (1, 4, 32)), jnp.int32),
        "loss_mask": jnp.ones((1, 4, 32), jnp.float32),
        "attention_mask": jnp.ones((1, 4, 32), jnp.int32),
    }
    losses = []
    for _ in range(8):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], f"MoE loss did not decrease: {losses}"


def test_hf_io_roundtrip_moe():
    """Stacked expert leaves <-> HF Mixtral per-expert names, bit-exact."""
    from llm_fine_tune_distributed_tpu.models.hf_io import (
        hf_state_dict_to_pytree,
        pytree_to_hf_state_dict,
    )
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict

    config = get_preset("tiny_moe")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    state = pytree_to_hf_state_dict(params)
    # per-expert names exist with torch [out, in] layout
    assert "model.layers.0.block_sparse_moe.experts.0.w1.weight" in state
    assert state["model.layers.0.block_sparse_moe.experts.0.w1.weight"].shape == (128, 64)
    assert "model.layers.0.block_sparse_moe.gate.weight" in state
    back = hf_state_dict_to_pytree(state, config)
    a, b = flatten_dict(params), flatten_dict(back)
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)


def test_mixtral_8x7b_traces():
    """Config #6-style scale check: param count and a full abstract train
    step on an fsdp x expert mesh (cf. tests/test_big_configs.py)."""
    from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
    from llm_fine_tune_distributed_tpu.parallel.optimizer import build_optimizer
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.train.state import TrainState
    from llm_fine_tune_distributed_tpu.train.step import build_train_step
    from llm_fine_tune_distributed_tpu.utils.tree import split_by_mask

    mc = get_preset("mixtral_8x7b")
    assert mc.num_params == pytest.approx(46.7e9, rel=0.01)
    tc = TrainConfig(
        model_preset="mixtral_8x7b",
        remat_policy="full",  # memory-limited recipe: minimum-HBM remat
        max_seq_length=1024,
        gradient_accumulation_steps=2,
        loss_chunk_size=512,
        attention_impl="xla",
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=1, expert=4),
    )
    params = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    )
    mask = trainable_mask(params, mc, tc)
    trainable, frozen = split_by_mask(params, mask)
    optimizer = build_optimizer(tc, None, total_steps=10, data_parallel_size=1)
    opt_state = jax.eval_shape(optimizer.init, trainable)
    state = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        trainable=trainable,
        frozen=frozen,
        opt_state=opt_state,
    )
    batch = {
        "input_ids": jax.ShapeDtypeStruct((2, 2, 1024), jnp.int32),
        "loss_mask": jax.ShapeDtypeStruct((2, 2, 1024), jnp.float32),
        "attention_mask": jax.ShapeDtypeStruct((2, 2, 1024), jnp.int32),
    }
    step = build_train_step(mc, tc, optimizer)
    new_state, metrics = jax.eval_shape(step, state, batch)
    assert metrics["loss"].shape == ()


def test_expert_weights_get_expert_axis_spec():
    """Sharding rules give stacked expert leaves a leading expert axis."""
    from llm_fine_tune_distributed_tpu.parallel.sharding import param_spec

    spec = param_spec("model/layers/0/block_sparse_moe/experts/w1", 3)
    assert spec[0] == "expert"
    spec2 = param_spec("model/layers/0/block_sparse_moe/experts/w2", 3)
    assert spec2[0] == "expert"


@pytest.mark.slow
def test_pipeline_moe_matches_plain(eight_devices):
    """GPipe schedule on tiny_moe == plain forward (logits AND router aux):
    capacity queues are per batch row, so microbatching changes nothing."""
    from llm_fine_tune_distributed_tpu.parallel.pipeline import (
        pipeline_forward,
        stack_stage_params,
        stage_sharding,
    )

    config = get_preset("tiny_moe")
    from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params

    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    ids = jnp.asarray(
        np.random.RandomState(8).randint(0, config.vocab_size, (4, 32)), jnp.int32
    )
    mesh = Mesh(np.array(eight_devices[:2]), ("pipe",))
    stacked = jax.device_put(
        stack_stage_params(params, config, 2), stage_sharding(mesh)
    )
    logits_pipe, aux_pipe = pipeline_forward(
        params, stacked, ids, config, mesh, 2,
        compute_dtype=jnp.float32, remat_blocks=False, return_aux=True,
    )
    logits_plain, _ = forward(
        params, ids, config, compute_dtype=jnp.float32, logits_dtype=jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(logits_pipe), np.asarray(logits_plain), atol=2e-4, rtol=2e-4
    )
    # aux statistics are nonlinear in the token distribution, so the pipeline
    # (mean of per-microbatch auxes — the same semantics the grad-accum scan
    # gives the plain path) must equal forward() run per microbatch
    per_mb = []
    for m in range(2):
        _, _, a = forward(
            params, ids[m * 2 : (m + 1) * 2], config,
            compute_dtype=jnp.float32, return_aux=True,
        )
        per_mb.append(float(a))
    np.testing.assert_allclose(float(aux_pipe), np.mean(per_mb), rtol=1e-5)


@pytest.mark.slow
def test_dpo_moe_train_step_converges():
    """DPO on tiny_moe: the policy's router aux joins the train objective
    (layer-mean scale) and rewards_accuracy climbs over a few steps."""
    from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
    from llm_fine_tune_distributed_tpu.parallel.optimizer import build_optimizer
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.train.dpo import build_dpo_train_step
    from llm_fine_tune_distributed_tpu.train.state import TrainState
    from llm_fine_tune_distributed_tpu.utils.tree import split_by_mask

    config = get_preset("tiny_moe")
    tc = TrainConfig(
        model_preset="tiny_moe",
        objective="dpo",
        per_device_batch_size=2,
        gradient_accumulation_steps=1,
        max_seq_length=32,
        learning_rate=5e-3,
        freeze_strategy="none",
        gradient_checkpointing=False,
        attention_impl="xla",
    )
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    mask = trainable_mask(params, config, tc)
    trainable, frozen = split_by_mask(params, mask)
    ref = {k: jnp.asarray(v, jnp.bfloat16) for k, v in trainable.items()}
    optimizer = build_optimizer(tc, None, total_steps=10, data_parallel_size=1)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        trainable=trainable,
        frozen=frozen,
        opt_state=optimizer.init(trainable),
    )
    step = jax.jit(build_dpo_train_step(config, tc, optimizer))
    rng = np.random.RandomState(0)
    batch = {}
    for side in ("chosen", "rejected"):
        batch[f"{side}_input_ids"] = jnp.asarray(
            rng.randint(0, 512, (1, 2, 32)), jnp.int32
        )
        batch[f"{side}_loss_mask"] = jnp.ones((1, 2, 32), jnp.float32)
        batch[f"{side}_attention_mask"] = jnp.ones((1, 2, 32), jnp.float32)
    losses = []
    for _ in range(6):
        state, metrics = step(state, ref, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], f"DPO-MoE loss did not decrease: {losses}"
    assert float(metrics["rewards_accuracy"]) >= 0.5


def test_padding_excluded_from_routing():
    """Pad tokens get zero MoE output, hold no capacity, and the aux loss
    equals the trimmed batch's aux exactly."""
    config = _cfg()
    lp = init_moe_params(jax.random.PRNGKey(0), config, jnp.float32)
    real_len = 6
    x_real = jnp.asarray(np.random.RandomState(4).randn(2, real_len, 16), jnp.float32)
    x_pad = jnp.concatenate(
        [x_real, jnp.asarray(np.random.RandomState(5).randn(2, 10, 16), jnp.float32)],
        axis=1,
    )
    mask = jnp.concatenate(
        [jnp.ones((2, real_len), jnp.int32), jnp.zeros((2, 10), jnp.int32)], axis=1
    )
    y_pad, aux_pad = moe_mlp(lp, x_pad, config, jnp.float32, token_mask=mask)
    y_ref, aux_ref = moe_mlp(lp, x_real, config, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(y_pad)[:, :real_len], np.asarray(y_ref), atol=1e-5
    )
    assert np.abs(np.asarray(y_pad)[:, real_len:]).max() == 0.0  # pads untouched
    np.testing.assert_allclose(float(aux_pad), float(aux_ref), rtol=1e-5)


def test_chunked_dispatch_matches_unchunked():
    """Grouped (chunked-sequence) routing == single-group routing when
    capacity is ample — the long-context memory path changes nothing
    numerically."""
    import dataclasses

    config = _cfg()  # moe_dispatch_chunk default 1024 >> s: single group
    chunked = dataclasses.replace(config, moe_dispatch_chunk=16)
    lp = init_moe_params(jax.random.PRNGKey(0), config, jnp.float32)
    x = jnp.asarray(np.random.RandomState(6).randn(2, 64, 16), jnp.float32)
    y_ref, aux_ref = moe_mlp(lp, x, config, jnp.float32)
    y_chk, aux_chk = jax.jit(lambda lp, x: moe_mlp(lp, x, chunked, jnp.float32))(lp, x)
    np.testing.assert_allclose(np.asarray(y_chk), np.asarray(y_ref), atol=1e-5)
    np.testing.assert_allclose(float(aux_chk), float(aux_ref), rtol=1e-5)

    # non-divisible length: 60 pads to 64 (4 chunks of 16), tail masked out
    x60 = x[:, :60]
    y_ref60, aux_ref60 = moe_mlp(lp, x60, config, jnp.float32)
    y60, aux60 = jax.jit(lambda lp, x: moe_mlp(lp, x, chunked, jnp.float32))(lp, x60)
    assert y60.shape == x60.shape
    np.testing.assert_allclose(np.asarray(y60), np.asarray(y_ref60), atol=1e-5)
    np.testing.assert_allclose(float(aux60), float(aux_ref60), rtol=1e-5)


def test_moe_dropless_matches_reference():
    """dropless=True must ignore capacity entirely: even with a degenerate
    capacity_factor the output equals the per-token reference loop."""
    config = _cfg(capacity_factor=1e-6)
    lp = init_moe_params(jax.random.PRNGKey(0), config, jnp.float32)
    x = jnp.asarray(np.random.RandomState(7).randn(2, 12, 16), jnp.float32)
    y, _ = jax.jit(
        lambda lp, x: moe_mlp(lp, x, config, jnp.float32, dropless=True)
    )(lp, x)
    np.testing.assert_allclose(
        np.asarray(y), _reference_moe(lp, x, config), atol=1e-5
    )


@pytest.mark.slow
def test_moe_kv_cache_decode_matches_full_forward():
    """Greedy KV-cache decode on tiny_moe == re-running the growing prefix
    through the cache-free forward. The decode path is dropless (HF Mixtral
    semantics), so the reference forward runs with ample capacity to be
    dropless too — then the cache must be numerically transparent."""
    import dataclasses

    from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
    from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
    from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params

    mc = get_preset("tiny_moe")
    mc_ample = dataclasses.replace(mc, capacity_factor=4.0)  # cap >= s: dropless
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    tok = ByteChatMLTokenizer()
    gen = Generator(params, mc, tok, compute_dtype=jnp.float32, eos_token_ids=[])
    prompt = tok.encode("water purification")
    cfg = GenerationConfig(max_new_tokens=6, do_sample=False, repetition_penalty=1.0)
    out = gen.generate_ids(prompt, cfg)
    assert len(out) == 6

    seq = list(prompt)
    for tok_id in out:
        logits, _ = forward(
            params, jnp.asarray([seq], jnp.int32), mc_ample, compute_dtype=jnp.float32
        )
        assert int(jnp.argmax(logits[0, -1])) == tok_id
        seq.append(tok_id)


def test_stacked_nf4_roundtrip_matches_per_expert():
    """quantize_nf4_stacked on [E, in, out] must equal quantizing each
    expert standalone — block grids never cross expert boundaries."""
    from llm_fine_tune_distributed_tpu.ops.nf4 import (
        dequantize_nf4,
        dequantize_nf4_stacked,
        quantize_nf4,
        quantize_nf4_stacked,
    )

    rng = np.random.RandomState(9)
    w = rng.randn(3, 128, 32).astype(np.float32)
    for dq in (False, True):
        qs = quantize_nf4_stacked(jnp.asarray(w), 64, dq)
        back = np.asarray(dequantize_nf4_stacked(qs, dtype=jnp.float32))
        assert back.shape == w.shape
        for e in range(3):
            ref = np.asarray(
                dequantize_nf4(quantize_nf4(w[e], 64, dq), dtype=jnp.float32)
            )
            if dq:
                # double-quant groups span experts, so scales differ slightly
                np.testing.assert_allclose(back[e], ref, atol=0.05)
            else:
                np.testing.assert_array_equal(back[e], ref)
        # reconstruction error bounded (NF4 at block 64 on N(0,1) data)
        assert np.abs(back - w).max() < 0.6


def test_qlora_moe_quantizes_experts():
    """quantize_frozen NF4-packs stacked expert weights and the dequant
    inverse restores them for export."""
    from llm_fine_tune_distributed_tpu.parallel.qlora import (
        dequantize_frozen,
        quantize_frozen,
        quantized_fraction,
    )
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.utils.tree import flatten_dict

    config = get_preset("tiny_moe")
    params = flatten_dict(init_params(jax.random.PRNGKey(0), config, jnp.float32))
    frozen = {k: v for k, v in params.items() if "/layers/" in k}
    q = quantize_frozen(frozen)
    assert "model/layers/0/block_sparse_moe/experts/w1_nf4" in q
    assert "model/layers/0/block_sparse_moe/experts/w1" not in q
    assert q["model/layers/0/block_sparse_moe/experts/w1_nf4"].shape == (4, 8, 128)
    assert quantized_fraction(q) > 0.5
    back = dequantize_frozen(q, dtype=jnp.float32)
    assert set(back) == set(frozen)
    w1 = np.asarray(frozen["model/layers/0/block_sparse_moe/experts/w1"])
    w1_back = np.asarray(back["model/layers/0/block_sparse_moe/experts/w1"])
    assert w1_back.shape == w1.shape
    assert np.abs(w1 - w1_back).max() < 0.1  # NF4 reconstruction error


@pytest.mark.slow
def test_qlora_moe_trainer_e2e(tmp_path):
    """Full QLoRA training on tiny_moe: adapters train against an
    NF4-quantized base (experts included), artifacts export."""
    import json

    from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data = tmp_path / "data"
    data.mkdir()
    jsonl = data / "qa.jsonl"
    with open(jsonl, "w") as f:
        for i in range(32):
            f.write(
                json.dumps({"topic": "Fire", "question": f"q {i}?", "answer": f"a {i}"})
                + "\n"
            )
    convert_jsonl_to_parquet(str(jsonl), str(data / "qa_dataset.parquet"), verbose=False)

    tc = TrainConfig(
        model_preset="tiny_moe",
        model_name="tiny-random",
        tokenizer_path="byte-chatml",
        data_dir=str(data),
        output_dir=str(tmp_path / "out"),
        epochs=1,
        per_device_batch_size=2,
        gradient_accumulation_steps=2,
        max_seq_length=64,
        eval_steps=100,
        save_steps=100,
        freeze_strategy="qlora",
        attention_impl="xla",
        mesh=MeshConfig(data=1, fsdp=1, tensor=1, seq=1, expert=1),
    )
    trainer = SFTTrainer(tc)
    assert any(k.endswith("experts/w1_nf4") for k in trainer.state.frozen)
    assert all(k.endswith(("lora_a", "lora_b")) for k in trainer.state.trainable)
    trainer.train()
    losses = [h["loss"] for h in trainer.metrics.history if "loss" in h]
    assert losses and np.isfinite(losses).all()
    assert (tmp_path / "out" / "best_model" / "model.safetensors").exists()


@pytest.mark.slow
def test_trainer_e2e_with_expert_axis(tmp_path):
    """SFTTrainer glue with a live expert axis: 8-device mesh
    (data=2, fsdp=2, expert=2), tiny_moe, full training loop + artifacts."""
    import json

    from llm_fine_tune_distributed_tpu.data.convert import convert_jsonl_to_parquet
    from llm_fine_tune_distributed_tpu.train.trainer import SFTTrainer

    data = tmp_path / "data"
    data.mkdir()
    jsonl = data / "qa.jsonl"
    with open(jsonl, "w") as f:
        for i in range(48):
            f.write(
                json.dumps(
                    {"topic": "Knots", "question": f"q {i}?", "answer": f"a {i} " + "w " * 5}
                )
                + "\n"
            )
    convert_jsonl_to_parquet(str(jsonl), str(data / "qa_dataset.parquet"), verbose=False)

    tc = TrainConfig(
        model_preset="tiny_moe",
        model_name="tiny-random",
        tokenizer_path="byte-chatml",
        data_dir=str(data),
        output_dir=str(tmp_path / "out"),
        epochs=1,
        per_device_batch_size=1,
        gradient_accumulation_steps=2,
        max_seq_length=64,
        eval_steps=100,
        save_steps=100,
        freeze_strategy="none",
        attention_impl="xla",
        mesh=MeshConfig(data=2, fsdp=2, tensor=1, seq=1, expert=2),
    )
    trainer = SFTTrainer(tc)
    assert trainer.mesh.shape["expert"] == 2
    trainer.train()
    losses = [h["loss"] for h in trainer.metrics.history if "loss" in h]
    assert losses and np.isfinite(losses).all()
    assert (tmp_path / "out" / "best_model" / "model.safetensors").exists()


@pytest.mark.slow
def test_mixtral_8x7b_qlora_traces():
    """QLoRA at 8x7B scale, abstractly: experts quantize to the NF4 layout
    (only adapters trainable), and the full train step traces."""
    from llm_fine_tune_distributed_tpu.parallel.freeze import trainable_mask
    from llm_fine_tune_distributed_tpu.parallel.lora import add_lora_from_config
    from llm_fine_tune_distributed_tpu.parallel.optimizer import build_optimizer
    from llm_fine_tune_distributed_tpu.parallel.qlora import quantize_frozen_abstract
    from llm_fine_tune_distributed_tpu.models.transformer import init_params
    from llm_fine_tune_distributed_tpu.train.state import TrainState
    from llm_fine_tune_distributed_tpu.train.step import build_train_step
    from llm_fine_tune_distributed_tpu.utils.tree import split_by_mask

    mc = get_preset("mixtral_8x7b")
    tc = TrainConfig(
        model_preset="mixtral_8x7b",
        remat_policy="full",
        max_seq_length=1024,
        gradient_accumulation_steps=2,
        loss_chunk_size=512,
        attention_impl="xla",
        freeze_strategy="qlora",
        quant_matmul_impl="xla",
        mesh=MeshConfig(data=1, fsdp=2, tensor=1, seq=1, expert=4),
    )
    params = jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), mc, jnp.float32))
    params = jax.eval_shape(
        lambda: add_lora_from_config(
            jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params),
            jax.random.PRNGKey(0),
            tc,
        )
    )
    mask = trainable_mask(params, mc, tc)
    trainable, frozen = split_by_mask(params, mask)
    frozen = quantize_frozen_abstract(
        {k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in frozen.items()},
        tc.quant_block_size,
        tc.quant_double_quant,
    )
    # experts packed: [E, in/8, out] int32; router gate NOT quantized
    k1 = "model/layers/0/block_sparse_moe/experts/w1_nf4"
    assert frozen[k1].shape == (8, 4096 // 8, 14336)
    assert frozen[k1].dtype == jnp.int32
    assert "model/layers/0/block_sparse_moe/gate/kernel" in frozen
    # memory at rest: quantized frozen bytes ~4.5 bits/param of 46.7B
    frozen_bytes = sum(
        int(np.prod(v.shape)) * v.dtype.itemsize for v in frozen.values()
    )
    assert frozen_bytes < 30e9, f"{frozen_bytes / 1e9:.1f} GB frozen (want < 30 GB)"

    optimizer = build_optimizer(tc, None, total_steps=10, data_parallel_size=2)
    opt_state = jax.eval_shape(optimizer.init, trainable)
    state = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        trainable=trainable,
        frozen=frozen,
        opt_state=opt_state,
    )
    batch = {
        "input_ids": jax.ShapeDtypeStruct((2, 2, 1024), jnp.int32),
        "loss_mask": jax.ShapeDtypeStruct((2, 2, 1024), jnp.float32),
        "attention_mask": jax.ShapeDtypeStruct((2, 2, 1024), jnp.int32),
    }
    step = build_train_step(mc, tc, optimizer)
    new_state, metrics = jax.eval_shape(step, state, batch)
    assert metrics["loss"].shape == ()
    assert all(k.endswith(("lora_a", "lora_b")) for k in state.trainable)


def test_moe_with_ring_attention_matches_unsharded(eight_devices):
    """MoE x sequence parallelism on a FLAT mesh (VERDICT r3 missing #3):
    a live seq axis with ring attention must not change MoE semantics —
    logits AND router aux (capacity/dispatch identical: the MoE runs in
    global view under GSPMD, only attention shard_maps over seq)."""
    from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params

    config = get_preset("tiny_moe")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 512, (2, 64)), jnp.int32)
    ref, _, aux_ref = forward(
        params, ids, config, attention_impl="xla", compute_dtype=jnp.float32,
        return_aux=True,
    )

    mesh = Mesh(
        np.array(eight_devices).reshape(2, 1, 1, 4, 1),
        ("data", "fsdp", "tensor", "seq", "expert"),
    )
    act = NamedSharding(mesh, P(("data", "fsdp"), "seq", None))
    with assert_seq_parallel("ring"):
        out, _, aux = jax.jit(
            lambda p, i: forward(
                p, i, config, attention_impl="ring", compute_dtype=jnp.float32,
                activation_sharding=act, return_aux=True,
            )
        )(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-4)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_moe_with_ulysses_attention_matches_unsharded(eight_devices):
    """Companion to the ring case: Ulysses all-to-all over seq with MoE."""
    from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params

    config = get_preset("tiny_moe")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 512, (2, 64)), jnp.int32)
    ref, _, aux_ref = forward(
        params, ids, config, attention_impl="xla", compute_dtype=jnp.float32,
        return_aux=True,
    )

    # mesh must SATISFY seq_parallel_preconditions (batch 2 % (data*fsdp) == 0,
    # kv heads 2 % seq 2 == 0) — the r4 version used data=2 x fsdp=2 with
    # batch 2, which silently tested the fallback (VERDICT r4 weak #1); the
    # guard makes any such regression fail loudly instead of passing.
    mesh = Mesh(
        np.array(eight_devices).reshape(2, 1, 1, 2, 2),
        ("data", "fsdp", "tensor", "seq", "expert"),
    )
    act = NamedSharding(mesh, P(("data", "fsdp"), "seq", None))
    with assert_seq_parallel("ulysses"):
        out, _, aux = jax.jit(
            lambda p, i: forward(
                p, i, config, attention_impl="ulysses", compute_dtype=jnp.float32,
                activation_sharding=act, return_aux=True,
            )
        )(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-4)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_moe_seq_axis_with_expert_axis_matches_unsharded(eight_devices):
    """seq x expert together: ring attention over seq while expert weights
    shard over the expert axis — the full long-context MoE mesh family."""
    from llm_fine_tune_distributed_tpu.models.transformer import forward, init_params

    config = get_preset("tiny_moe")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 512, (2, 64)), jnp.int32)
    ref, _, aux_ref = forward(
        params, ids, config, attention_impl="xla", compute_dtype=jnp.float32,
        return_aux=True,
    )

    mesh = Mesh(
        np.array(eight_devices).reshape(2, 1, 1, 2, 2),
        ("data", "fsdp", "tensor", "seq", "expert"),
    )
    from llm_fine_tune_distributed_tpu.parallel.sharding import shard_params

    params_sharded = shard_params(params, mesh)
    act = NamedSharding(mesh, P(("data", "fsdp"), "seq", None))
    with assert_seq_parallel("ring"):
        out, _, aux = jax.jit(
            lambda p, i: forward(
                p, i, config, attention_impl="ring", compute_dtype=jnp.float32,
                activation_sharding=act, return_aux=True,
            )
        )(params_sharded, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-4)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)
