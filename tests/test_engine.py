"""Continuous-batching engine (infer/engine.py): slot-based persistent
decode with in-flight admission. Pins the contracts the window batcher
could not offer: greedy bit-parity with solo decode WHILE other slots are
live, FIFO admission across mixed greedy/sampled traffic, slot reuse after
EOS, and abandoned requests shed without decoding."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_fine_tune_distributed_tpu.data.tokenizer import ByteChatMLTokenizer
from llm_fine_tune_distributed_tpu.infer import GenerationConfig, Generator
from llm_fine_tune_distributed_tpu.infer.engine import ContinuousBatchingEngine
from llm_fine_tune_distributed_tpu.infer.sampling import (
    sample_token,
    sample_token_traced,
)
from llm_fine_tune_distributed_tpu.models.configs import get_preset
from llm_fine_tune_distributed_tpu.models.transformer import init_params

GREEDY = GenerationConfig(max_new_tokens=6, do_sample=False)
SAMPLED = GenerationConfig(max_new_tokens=6, do_sample=True, temperature=1.0)


@pytest.fixture(scope="module")
def generator():
    mc = get_preset("tiny")
    params = init_params(jax.random.PRNGKey(0), mc, dtype=jnp.float32)
    return Generator(
        params, mc, ByteChatMLTokenizer(), compute_dtype=jnp.float32, eos_token_ids=[]
    )


@pytest.fixture()
def engine(generator):
    return ContinuousBatchingEngine(generator, slots=4, buf_len=96, prompt_bucket=16)


def _prompts():
    tok = ByteChatMLTokenizer()
    return [tok.encode(t) for t in ("alpha", "beta bravo", "the quick brown fox")]


def test_greedy_bit_identical_to_solo_with_live_neighbors(generator, engine):
    """The headline guarantee: a greedy request decoded in a slot whose
    neighbors are live (including SAMPLED ones — impossible to co-batch in
    the window engine) produces exactly solo generate_ids' tokens."""
    prompts = _prompts()
    solo = [generator.generate_ids(p, GREEDY) for p in prompts]

    long_cfg = GenerationConfig(max_new_tokens=48, do_sample=True, temperature=1.0)
    results = [None] * len(prompts)

    def occupy():
        engine.submit(prompts[0], long_cfg, seed=11, timeout=240)

    def ask(i):
        results[i] = engine.submit(prompts[i], GREEDY, timeout=240)

    occupier = threading.Thread(target=occupy)
    occupier.start()
    time.sleep(0.05)  # let the sampled occupant take its slot first
    threads = [threading.Thread(target=ask, args=(i,)) for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads + [occupier]:
        t.join(timeout=240)
    assert results == solo


def test_sampled_deterministic_in_request_seed(engine):
    """Per-slot RNG is keyed by the REQUEST seed, not the row index: the
    same (prompt, config, seed) reproduces regardless of slot placement or
    co-residents — the property that lifts the window engine's
    greedy-only co-batching restriction."""
    prompts = _prompts()
    runs = []
    for round_ in range(2):
        results = [None] * 3
        seeds = [5, 5, 9]

        def ask(i):
            results[i] = engine.submit(prompts[0], SAMPLED, seed=seeds[i], timeout=240)

        # different co-resident mixes each round (a greedy neighbor in round
        # two) must not change any sampled row's tokens
        extra = None
        if round_ == 1:
            extra = threading.Thread(
                target=lambda: engine.submit(prompts[2], GREEDY, timeout=240)
            )
            extra.start()
        threads = [threading.Thread(target=ask, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        for t in threads + ([extra] if extra else []):
            t.join(timeout=240)
        runs.append(results)
    assert runs[0][0] == runs[0][1]  # same seed -> same tokens
    assert runs[0][0] != runs[0][2]  # different seed -> different draw
    assert runs[0] == runs[1]  # co-resident mix is irrelevant


def test_fifo_admission_mixed_traffic(generator):
    """With one slot occupied, a SAMPLED waiter that arrived before a
    greedy waiter is admitted first — the continuous engine has no
    compatibility classes to jump the queue with."""
    engine = ContinuousBatchingEngine(generator, slots=1, buf_len=96, prompt_bucket=16)
    prompts = _prompts()
    done_at = {}

    def ask(name, delay, prompt, cfg):
        def run():
            time.sleep(delay)
            engine.submit(prompt, cfg, timeout=240)
            done_at[name] = time.monotonic()

        t = threading.Thread(target=run)
        t.start()
        return t

    threads = [
        ask("occupant", 0.0, prompts[0], GenerationConfig(max_new_tokens=24, do_sample=False)),
        ask("sampled", 0.10, prompts[1], SAMPLED),
        ask("greedy", 0.20, prompts[2], GREEDY),
    ]
    for t in threads:
        t.join(timeout=240)
    assert done_at["sampled"] < done_at["greedy"], done_at


def test_slot_reuse_after_eos(generator):
    """A slot whose row hits EOS frees immediately and is re-prefilled for
    the next waiter; the EOS-truncated result matches solo decode with the
    same EOS set."""
    prompts = _prompts()
    solo_open = generator.generate_ids(prompts[0], GREEDY)
    # promote one emitted token to EOS; truncation happens at its FIRST
    # occurrence (the tiny random-init model may repeat tokens, so derive
    # the expectation rather than assuming distinct greedy tokens)
    eos = solo_open[-1]
    gen_eos = Generator(
        generator.params, generator.config, ByteChatMLTokenizer(),
        compute_dtype=jnp.float32, eos_token_ids=[eos],
    )
    solo = gen_eos.generate_ids(prompts[0], GREEDY)
    assert solo == solo_open[: solo_open.index(eos)]  # sanity: EOS truncates

    engine = ContinuousBatchingEngine(gen_eos, slots=2, buf_len=96, prompt_bucket=16)
    # 5 requests through 2 slots: slots MUST be reused (incl. after EOS)
    results = [engine.submit(prompts[0], GREEDY, timeout=240) for _ in range(5)]
    assert all(r == solo for r in results)
    snap = engine.stats_snapshot()
    assert snap["requests_completed"] == 5
    assert snap["live_slots"] == 0 and snap["queue_depth"] == 0


def test_abandoned_request_dropped_without_decoding(generator):
    """A submit that times out while QUEUED is never admitted (no prefill,
    no decode for a waiter that's gone) — the window engine's abandonment
    semantics, carried over."""
    # buf_len=128 is unique to this test: the occupier's prefill/step jits
    # compile fresh INSIDE its admission, so the short-timeout waiter below
    # reliably expires while still queued (no warm-cache race)
    engine = ContinuousBatchingEngine(generator, slots=1, buf_len=128, prompt_bucket=16)
    prompts = _prompts()
    long_cfg = GenerationConfig(max_new_tokens=48, do_sample=False)
    occupier = threading.Thread(
        target=lambda: engine.submit(prompts[0], long_cfg, timeout=240)
    )
    occupier.start()
    time.sleep(0.05)
    with pytest.raises(TimeoutError):
        engine.submit(prompts[1], GREEDY, timeout=0.2)
    occupier.join(timeout=240)
    # drain: one more request proves the engine is healthy afterwards
    assert engine.submit(prompts[2], GREEDY, timeout=240) is not None
    snap = engine.stats_snapshot()
    assert snap["requests_abandoned"] == 1
    # the abandoned request was never admitted, so exactly two were
    assert snap["requests_admitted"] == 2
    assert snap["tokens_served"] == 48 + 6


def test_streaming_rides_the_batch(generator, engine):
    """stream() yields the same greedy tokens solo decode produces, one at
    a time, while a neighbor slot decodes concurrently."""
    prompts = _prompts()
    solo = generator.generate_ids(prompts[1], GREEDY)
    neighbor = threading.Thread(
        target=lambda: engine.submit(
            prompts[0], GenerationConfig(max_new_tokens=24, do_sample=True), timeout=240
        )
    )
    neighbor.start()
    got = list(engine.stream(prompts[1], GREEDY, timeout=120))
    neighbor.join(timeout=240)
    assert got == solo


def test_error_propagates_to_waiter(generator):
    engine = ContinuousBatchingEngine(generator, slots=2, buf_len=96, prompt_bucket=16)
    with pytest.raises(ValueError):
        engine.submit([], GREEDY, timeout=30)  # empty prompt
    with pytest.raises(ValueError):
        engine.submit(list(range(200)), GREEDY, timeout=30)  # exceeds buf_len


def test_traced_sampler_greedy_matches_static():
    """sample_token_traced's greedy path is bitwise the static sampler's
    (the engine's parity guarantee reduces to this plus row-independence
    of the forward)."""
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(3, 97), jnp.float32)
    seen = jnp.asarray(rng.rand(3, 97) < 0.3)
    cfg = GenerationConfig(do_sample=False, repetition_penalty=1.3)
    want = sample_token(None, logits, seen, cfg)
    keys = jnp.stack([jax.random.PRNGKey(i) for i in range(3)])
    got = sample_token_traced(
        keys, logits, seen,
        temperature=jnp.full((3,), 0.7),
        top_p=jnp.full((3,), 0.9),
        top_k=jnp.full((3,), 40, jnp.int32),
        repetition_penalty=jnp.full((3,), 1.3),
        do_sample=jnp.zeros((3,), bool),
    )
    assert np.array_equal(np.asarray(want), np.asarray(got))
